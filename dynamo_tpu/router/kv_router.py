"""The KV-aware router and its pipeline sink
(ref: lib/llm/src/kv_router.rs:185 ``KvRouter``, :423 ``KvPushRouter``).

``KvRouter`` owns the prefix indexer (event-fed, with the approximate
fallback), the potential-load tracker, and the event subscription; the
``KvPushRouter`` sink plugs into the LLM pipeline in place of the
round-robin ``PushSink`` and performs route → push → track → free.
"""

from __future__ import annotations

import asyncio
import random
import uuid
from typing import Any, AsyncIterator, Dict, Optional, Set

import msgpack

from ..runtime.circuit import CircuitBreakerRegistry
from ..runtime.component import Client, Component
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..runtime.transport import (
    EngineError, ERR_DRAINING, ERR_OVERLOADED, ERR_UNAVAILABLE,
)
from ..tracing import trace_span
from ..utils.logging import get_logger
from ..tokens import compute_block_hashes_for_seq
from ..prefix.radix import TIER_G1, TIER_G2, TIER_G4, RadixPrefixIndex
from .indexer import ApproxKvIndexer, KvIndexer, RouterEvent
from .scheduler import KvRouterConfig, PotentialLoads, Selection, select_worker

log = get_logger("kv_router")

KV_EVENTS_SUBJECT = "kv_events"         # ref: kv_router.rs:60
LOAD_METRICS_SUBJECT = "load_metrics"   # ref: kv_router.rs:57
# inter-replica routing lifecycle sync (ref: kv_router.rs:65-73
# prefill_events + active_sequences_events — one subject here, the event
# carries the lifecycle kind)
ROUTER_SYNC_SUBJECT = "router_sync"


class KvRouter:
    """Routing brain: indexer + scheduler + event subscription
    (ref: kv_router.rs:185).

    ``use_events=False`` selects the ApproxKvIndexer (approx.rs:165): the
    router then learns prefix placement from its own decisions only.
    """

    # class-level default so partially-constructed fakes stay
    # forward-compatible as routing collaborators are added
    prefix_index = None

    def __init__(
        self,
        client: Client,
        component: Component,
        block_size: int,
        config: Optional[KvRouterConfig] = None,
        use_events: bool = True,
        seed: Optional[int] = None,
        breakers: Optional[CircuitBreakerRegistry] = None,
    ):
        self.client = client
        self.component = component
        self.block_size = block_size
        self.config = config or KvRouterConfig()
        self.indexer = KvIndexer(block_size) if use_events else None
        self.approx = None if use_events else ApproxKvIndexer(block_size)
        # cluster replica of the radix prefix index (prefix.radix), fed by
        # the same KV-event stream: find_best_match scores workers by
        # longest cached prefix, tier-weighted, for prefix-bearing requests
        self.prefix_index = (
            RadixPrefixIndex(block_size, tier_weights={
                TIER_G1: 1.0,
                TIER_G2: self.config.prefix_tier_weight_g2,
                TIER_G4: self.config.prefix_tier_weight_g4,
            })
            if use_events and self.config.prefix_routing else None
        )
        self.loads = PotentialLoads(block_size)
        # per-worker circuit breakers: tripped workers are skipped during
        # selection until their half-open probe succeeds
        self.breakers = breakers or CircuitBreakerRegistry()
        # worker_id -> latest ForwardPassMetrics snapshot (kv_usage, queue
        # depths) from the load_metrics subject; drives busy-threshold
        # rejection (ref: push_router.rs:58-63)
        self.worker_stats: Dict[int, dict] = {}
        self._rng = random.Random(seed)
        self._sub_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._stream = None
        self._stats_stream = None
        # replica sync (ref: kv_router.rs:65-73)
        self.router_id = uuid.uuid4().hex
        self._sync_out: "asyncio.Queue[dict]" = asyncio.Queue()
        self._sync_pub_task: Optional[asyncio.Task] = None
        self._sync_sub_task: Optional[asyncio.Task] = None
        self._sync_stream = None
        # request ids applied from each peer, so a lost subscription can
        # roll back exactly the load we attributed to that peer
        self._peer_requests: Dict[str, Set[str]] = {}
        self.num_peer_events = 0
        self._events_at_snapshot = 0
        self._snapshot_task: Optional[asyncio.Task] = None
        # workers that answered ``draining``: divert-elsewhere until their
        # instance key is deleted (drain completed) or re-put (re-advertised)
        self.draining: Set[int] = set()
        client.on_instance_removed.append(self._on_worker_removed)
        client.on_instance_added.append(self._on_worker_added)

    # -- lifecycle --

    async def start(self) -> None:
        store = self.client.runtime.store
        if self._stats_task is None:
            self._stats_stream = await store.subscribe(
                self.component.event_subject(LOAD_METRICS_SUBJECT)
            )
            self._stats_task = asyncio.create_task(
                self._stats_loop(self._stats_stream)
            )
        if self.config.replica_sync and self._sync_sub_task is None:
            self._sync_stream = await store.subscribe(
                self.component.event_subject(ROUTER_SYNC_SUBJECT)
            )
            self._sync_sub_task = asyncio.create_task(
                self._sync_loop(self._sync_stream)
            )
            self._sync_pub_task = asyncio.create_task(self._sync_publisher())
        if self.indexer is None or self._sub_task is not None:
            return
        # subscribe BEFORE loading the snapshot: events published while the
        # snapshot is read buffer in the watch stream and are consumed only
        # after the (older) snapshot is applied — so removals that race the
        # warm-start still land on top, in order
        self._stream = await store.subscribe(
            self.component.event_subject(KV_EVENTS_SUBJECT)
        )
        await self._load_snapshot()
        self._sub_task = asyncio.create_task(self._event_loop(self._stream))

    async def stop(self) -> None:
        if self._snapshot_task is not None:
            try:
                await self._snapshot_task
            except Exception:
                pass
            self._snapshot_task = None
        for task_attr, stream_attr in (
            ("_sub_task", "_stream"), ("_stats_task", "_stats_stream"),
            ("_sync_sub_task", "_sync_stream"),
            ("_sync_pub_task", None),
        ):
            task = getattr(self, task_attr)
            if task is not None:
                task.cancel()
                setattr(self, task_attr, None)
            stream = getattr(self, stream_attr) if stream_attr else None
            if stream is not None:
                try:
                    await stream.cancel()
                except Exception:
                    pass
                setattr(self, stream_attr, None)
        try:
            self.client.on_instance_removed.remove(self._on_worker_removed)
        except ValueError:
            pass
        try:
            self.client.on_instance_added.remove(self._on_worker_added)
        except ValueError:
            pass

    async def _resubscribe(self, subject: str):
        store = self.client.runtime.store
        attempt = 0
        while True:
            try:
                return await store.subscribe(subject)
            except Exception as exc:
                # traceback once; during a store outage this retries every
                # 0.5s per topic and repeating it would drown the log
                if attempt == 0:
                    log.exception("resubscribe %s failed — retrying", subject)
                else:
                    log.warning("resubscribe %s failed (attempt %d): %s",
                                subject, attempt + 1, exc)
                attempt += 1
                await asyncio.sleep(0.5)

    async def _event_loop(self, stream) -> None:
        subject = self.component.event_subject(KV_EVENTS_SUBJECT)
        while True:
            event = await stream.next()
            if event is None or event["event"] == "dropped":
                # the store unregisters a shed/closed subscription — our
                # index may have missed events, so drop all state and
                # resubscribe; routing decisions rebuild it organically
                log.warning("kv_events subscription lost — resetting index")
                for w in list(self.client.instances):
                    self.indexer.clear_worker(w)
                    if self.prefix_index is not None:
                        self.prefix_index.drop_worker(w)
                await stream.cancel()
                stream = self._stream = await self._resubscribe(subject)
                continue
            if event["event"] != "msg":
                continue
            try:
                payload = msgpack.unpackb(event["value"], raw=False)
                ev = RouterEvent.from_dict(payload)
                self.indexer.apply_event(ev)
                if self.prefix_index is not None:
                    self.prefix_index.apply_event(
                        ev.worker_id, payload["event"])
                self._maybe_snapshot()
            except Exception:
                log.exception("bad kv event")

    async def _stats_loop(self, stream) -> None:
        subject = self.component.event_subject(LOAD_METRICS_SUBJECT)
        while True:
            event = await stream.next()
            if event is None or event["event"] == "dropped":
                await stream.cancel()
                stream = self._stats_stream = await self._resubscribe(subject)
                continue
            if event["event"] != "msg":
                continue
            try:
                snap = msgpack.unpackb(event["value"], raw=False)
                self.worker_stats[int(snap["worker_id"])] = snap
            except Exception:
                log.exception("bad load metrics event")

    # -- replica sync (ref: kv_router.rs:65-73) --

    def _sync_emit(self, kind: str, request_id: str, worker_id: int = 0,
                   isl: int = 0, overlap: int = 0) -> None:
        if self.config.replica_sync:
            self._sync_out.put_nowait({
                "router_id": self.router_id, "kind": kind,
                "request_id": request_id, "worker_id": worker_id,
                "isl": isl, "overlap": overlap,
            })

    async def _sync_publisher(self) -> None:
        store = self.client.runtime.store
        subject = self.component.event_subject(ROUTER_SYNC_SUBJECT)
        while True:
            msg = await self._sync_out.get()
            try:
                await store.publish(subject, msgpack.packb(msg))
            except Exception:
                log.exception("router sync publish failed")

    async def _sync_loop(self, stream) -> None:
        subject = self.component.event_subject(ROUTER_SYNC_SUBJECT)
        while True:
            event = await stream.next()
            if event is None or event["event"] == "dropped":
                # we may have missed peer lifecycle events (including
                # frees) — roll back everything we attributed to peers so
                # load can't leak, then resubscribe
                log.warning("router_sync subscription lost — "
                            "dropping peer-attributed load")
                for rids in self._peer_requests.values():
                    for rid in rids:
                        self.loads.free(rid)
                self._peer_requests.clear()
                await stream.cancel()
                stream = self._sync_stream = await self._resubscribe(subject)
                continue
            if event["event"] != "msg":
                continue
            try:
                msg = msgpack.unpackb(event["value"], raw=False)
                self._apply_peer_event(msg)
            except Exception:
                log.exception("bad router sync event")

    def _apply_peer_event(self, msg: dict) -> None:
        if msg.get("router_id") == self.router_id:
            return  # our own publication echoed back
        rid = msg["request_id"]
        kind = msg["kind"]
        peers = self._peer_requests.setdefault(msg["router_id"], set())
        self.num_peer_events += 1
        if kind == "add":
            peers.add(rid)
            self.loads.add(rid, int(msg["worker_id"]), int(msg["isl"]),
                           int(msg["overlap"]))
        elif kind == "prefill_done":
            self.loads.prefill_done(rid)
        elif kind == "free":
            peers.discard(rid)
            self.loads.free(rid)

    # -- index snapshot persistence (ref: kv_router.rs:979, indexer.rs:450) --

    def _snapshot_key(self) -> str:
        return f"v1/router/{self.component.path}/radix-snapshot"

    def _maybe_snapshot(self) -> None:
        thresh = self.config.snapshot_threshold
        if (not thresh or self.indexer is None
                or self._snapshot_task is not None):
            return
        if self.indexer.events_applied - self._events_at_snapshot < thresh:
            return
        self._events_at_snapshot = self.indexer.events_applied
        self._snapshot_task = asyncio.create_task(self._write_snapshot())

    async def _write_snapshot(self) -> None:
        """Persist the prefix index under a store lock so exactly one
        replica writes (ref: the etcd-locked radix-bucket writer)."""
        store = self.client.runtime.store
        lock_name = self._snapshot_key()
        try:
            if not await store.lock(lock_name):
                return  # a peer replica is writing — theirs is as good
            try:
                payload = msgpack.packb({
                    # str keys: msgpack's strict_map_key rejects int keys
                    "workers": {
                        str(w): sorted(hs)
                        for w, hs in self.indexer._hashes_of.items() if hs
                    },
                    "router_id": self.router_id,
                })
                await store.put(self._snapshot_key(), payload)
            finally:
                await store.unlock(lock_name)
        except Exception:
            log.exception("index snapshot write failed")
        finally:
            self._snapshot_task = None

    async def _load_snapshot(self) -> None:
        """Warm-start the prefix index from the persisted snapshot, keeping
        only workers that are still registered."""
        store = self.client.runtime.store
        try:
            raw = await store.get(self._snapshot_key())
        except Exception:
            log.exception("index snapshot read failed")
            return
        if not raw:
            return
        try:
            snap = msgpack.unpackb(raw, raw=False)
            try:  # give discovery a moment so the liveness filter is real
                await self.client.wait_for_instances(1, timeout_s=2.0)
            except Exception:
                pass
            live = set(self.client.instance_ids())
            loaded = 0
            for w, hashes in snap.get("workers", {}).items():
                w = int(w)
                if live and w not in live:
                    continue  # dead worker — its blocks are gone
                self.indexer.apply_event(RouterEvent(
                    worker_id=w, kind="stored", blocks=tuple(hashes),
                ))
                if self.prefix_index is not None:
                    # parent links aren't persisted — flat inserts still
                    # match (lookups walk the request's own hash chain)
                    self.prefix_index.apply_event(w, {
                        "kind": "stored",
                        "blocks": [{"seq_hash": h} for h in hashes],
                    })
                loaded += len(hashes)
            self._events_at_snapshot = self.indexer.events_applied
            log.info("index warm-start: %d blocks from snapshot", loaded)
        except Exception:
            log.exception("bad index snapshot — starting cold")

    def _on_worker_removed(self, worker_id: int) -> None:
        if self.indexer is not None:
            self.indexer.remove_worker(worker_id)
        if self.prefix_index is not None:
            self.prefix_index.drop_worker(worker_id)
        if self.approx is not None:
            self.approx.remove_worker(worker_id)
        self.loads.remove_worker(worker_id)
        self.worker_stats.pop(worker_id, None)
        self.breakers.remove(worker_id)
        self.draining.discard(worker_id)

    def _on_worker_added(self, worker_id: int) -> None:
        # a re-put of the instance key (health recovery re-advertisement)
        # means the worker takes traffic again
        self.draining.discard(worker_id)

    def mark_draining(self, worker_id: int) -> None:
        """Divert new work away from a worker that rejected with ``draining``
        (covers the race before its instance-key delete reaches our watch)."""
        self.draining.add(worker_id)

    # -- routing (ref: kv_router.rs:291 find_best_match) --

    def find_best_match(
        self,
        request_id: str,
        token_ids: list,
        *,
        overlap_weight: Optional[float] = None,
        temperature: Optional[float] = None,
    ) -> Selection:
        workers = self.client.instance_ids()
        if not workers:
            raise EngineError(
                f"no instances for {self.client.endpoint.path}",
                ERR_UNAVAILABLE,
            )
        # circuit-breaker filter: a tripped worker takes no traffic until its
        # open timeout elapses, then at most half_open_probes requests probe
        # it (allow() is non-mutating — the probe slot is reserved by begin()
        # only for the worker actually selected)
        admitted = [w for w in workers if self.breakers.allow(w)]
        if not admitted:
            raise EngineError(
                f"all {len(workers)} workers circuit-open",
                ERR_UNAVAILABLE,
            )
        workers = admitted
        # drain filter: a worker that answered ``draining`` takes no new
        # traffic; unlike a breaker this clears the moment its key is
        # deleted (drain done) or re-put (re-advertised)
        if self.draining:
            active = [w for w in workers if w not in self.draining]
            if not active:
                raise EngineError(
                    f"all {len(workers)} workers draining", ERR_UNAVAILABLE
                )
            workers = active
        # busy-threshold rejection (ref: push_router.rs:58-63): drop workers
        # whose published KV usage exceeds the threshold; if every worker is
        # saturated, reject so the frontend returns 503 instead of queueing
        if self.config.busy_threshold is not None:
            free = [
                w for w in workers
                if self.worker_stats.get(w, {}).get("kv_usage", 0.0)
                < self.config.busy_threshold
            ]
            if not free:
                raise EngineError(
                    f"all {len(workers)} workers above busy threshold "
                    f"{self.config.busy_threshold}", ERR_OVERLOADED,
                )
            workers = free
        hashes = compute_block_hashes_for_seq(token_ids, self.block_size)
        prefix_match = None
        if self.prefix_index is not None:
            pm = self.prefix_index.find_matches(hashes)
            if pm.blocks >= self.config.prefix_min_blocks and pm.scores:
                prefix_match = pm
        if prefix_match is not None:
            # tier-weighted longest-cached-prefix scores: a G1 run counts
            # full blocks, a host/store-held run counts fractionally (the
            # onboard copy it implies). Non-prefix-bearing requests (no
            # match, or shorter than prefix_min_blocks) keep the flat
            # block-hash-overlap scoring below.
            overlaps = prefix_match.scores
        elif self.indexer is not None:
            overlaps = self.indexer.find_matches(hashes).scores
        else:
            overlaps = self.approx.find_matches_for_tokens(token_ids).scores
        sel = select_worker(
            workers, len(token_ids), overlaps, self.loads, self.block_size,
            self.config, overlap_weight=overlap_weight,
            temperature=temperature, rng=self._rng,
        )
        if prefix_match is not None:
            # load accounting wants true cached-block counts on the chosen
            # worker, not the tier-weighted score
            sel = Selection(
                worker_id=sel.worker_id,
                overlap_blocks=prefix_match.worker_blocks.get(
                    sel.worker_id, 0),
                logit=sel.logit,
            )
        self.breakers.begin(sel.worker_id)
        self.loads.add(request_id, sel.worker_id, len(token_ids),
                       sel.overlap_blocks)
        self._sync_emit("add", request_id, sel.worker_id, len(token_ids),
                        sel.overlap_blocks)
        if self.approx is not None:
            self.approx.record_routing_decision(sel.worker_id, token_ids)
        log.debug(
            "selected worker %d logit=%.3f overlap=%d blocks",
            sel.worker_id, sel.logit, sel.overlap_blocks,
        )
        return sel

    def prefill_done(self, request_id: str) -> None:
        self.loads.prefill_done(request_id)
        self._sync_emit("prefill_done", request_id)

    def free(self, request_id: str) -> None:
        self.loads.free(request_id)
        self._sync_emit("free", request_id)


class KvPushRouter(AsyncEngine):
    """Pipeline sink: KV-aware route + direct push (ref: kv_router.rs:423).

    Accepts the preprocessed wire dict (``token_ids`` present), picks the
    worker via :class:`KvRouter`, streams from it, and maintains the
    potential-load lifecycle (prefill→decode on first item, free at end).
    Per-request ``router_hints`` override weight/temperature
    (ref: RouterConfigOverride kv_router.rs:87-93).
    """

    def __init__(self, router: KvRouter):
        self.router = router

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[Any]:
        # multimodal prompts route by their CONTENT-ADDRESSED hash ids —
        # the engine's KV events are keyed by those, while token_ids carry
        # only placeholder runs that could never match
        mm = request.get("mm") or {}
        token_ids = list(mm.get("hash_token_ids")
                         or request.get("token_ids", ()))
        hints: Dict[str, Any] = request.get("router_hints") or {}
        with trace_span("router.select", context) as span:
            sel = self.router.find_best_match(
                context.id, token_ids,
                overlap_weight=hints.get("overlap_score_weight"),
                temperature=hints.get("router_temperature"),
            )
            span.set_attr("worker_id", sel.worker_id)
            span.set_attr("overlap_blocks", sel.overlap_blocks)
        first = True
        healthy = False
        try:
            async for item in self.router.client.direct(
                sel.worker_id, request, context
            ):
                if first:
                    self.router.prefill_done(context.id)
                    first = False
                # any delivered frame proves the worker is alive; consumers
                # (e.g. Migration) may close this generator right after the
                # finished item, so success must not wait for exhaustion
                healthy = True
                yield item
        except EngineError as e:
            # only transport-level unavailability feeds the breaker;
            # overload/timeouts are load signals, not worker death, and
            # tripping on them would shrink capacity exactly when it is
            # most needed. A draining rejection is a planned divert: mark
            # the worker so retries route elsewhere, but never punish it
            if e.code == ERR_DRAINING:
                self.router.mark_draining(sel.worker_id)
            elif e.code == ERR_UNAVAILABLE:
                healthy = False
                self.router.breakers.record_failure(sel.worker_id)
                if self.router.approx is not None:
                    # the worker is gone but its lease may not have expired
                    # yet — without this purge the TTL'd decision history
                    # keeps steering retries of the same prefix back at the
                    # dead worker until remove_worker fires
                    self.router.approx.remove_worker(sel.worker_id)
            raise
        finally:
            if healthy:
                self.router.breakers.record_success(sel.worker_id)
            self.router.free(context.id)
