"""Prefix index over worker KV caches (ref: lib/llm/src/kv_router/indexer.rs).

The reference keeps a per-worker radix tree of *unchained* per-block hashes
(indexer.rs:224 ``RadixTree``) and walks it edge by edge. This build keys
every component on **chained sequence hashes** (see ``dynamo_tpu.tokens``
module docstring): equal sequence hashes imply equal full prefixes, so the
radix tree collapses into a flat ``seq_hash → {workers}`` map and prefix
matching is a linear walk over the request's block hashes — O(depth) with no
tree bookkeeping, and immune to cross-component hash-scheme drift.

``ApproxKvIndexer`` (ref: approx.rs:165) is the no-events fallback: it
records the router's *own* routing decisions with a TTL, approximating which
worker holds which prefix when engines don't publish events.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from ..tokens import SequenceHash, compute_block_hashes_for_seq

WorkerId = int


@dataclass
class OverlapScores:
    """Per-worker count of matched leading blocks (ref: indexer.rs:617)."""

    scores: Dict[WorkerId, int] = field(default_factory=dict)

    def best(self) -> int:
        return max(self.scores.values(), default=0)


@dataclass(frozen=True)
class RouterEvent:
    """One worker's KV-cache event as carried on the wire
    (ref: indexer.rs:175)."""

    worker_id: WorkerId
    kind: str                 # "stored" | "removed" | "cleared"
    blocks: tuple             # stored: ({seq_hash, block_hash, parent},…)
                              # removed: (seq_hash,…); cleared: ()

    def to_dict(self) -> dict:
        return {
            "worker_id": self.worker_id,
            "event": {"kind": self.kind, "blocks": list(self.blocks)},
        }

    @staticmethod
    def from_dict(d: dict) -> "RouterEvent":
        ev = d["event"]
        return RouterEvent(
            worker_id=int(d["worker_id"]),
            kind=ev["kind"],
            blocks=tuple(ev.get("blocks", ())),
        )


class KvIndexer:
    """seq_hash → set(workers) prefix index fed by KV events.

    Same role as the reference's ``KvIndexer`` + ``RadixTree``
    (indexer.rs:224,738); flat because our hashes chain (module docstring).
    """

    def __init__(self, block_size: int, use_native: Optional[bool] = None):
        self.block_size = block_size
        self._workers_of: Dict[SequenceHash, Set[WorkerId]] = {}
        self._hashes_of: Dict[WorkerId, Set[SequenceHash]] = {}
        self.events_applied = 0
        # C++ matcher for the per-decision hot loop (native/src); the Python
        # maps stay authoritative for dump_events/introspection
        self._native = None
        if use_native is not False:
            try:
                from ..native import NativePrefixIndex, available

                if available():
                    self._native = NativePrefixIndex()
            except Exception:
                self._native = None

    # -- event application (ref: indexer.rs:320 apply_event) --

    def apply_event(self, event: RouterEvent) -> None:
        self.events_applied += 1
        w = event.worker_id
        if event.kind == "stored":
            held = self._hashes_of.setdefault(w, set())
            fresh = []
            for b in event.blocks:
                h = int(b["seq_hash"]) if isinstance(b, dict) else int(b)
                if h not in held:
                    fresh.append(h)
                self._workers_of.setdefault(h, set()).add(w)
                held.add(h)
            if self._native is not None and fresh:
                self._native.stored(w, fresh)
        elif event.kind == "removed":
            held = self._hashes_of.get(w)
            gone = []
            for h in event.blocks:
                h = int(h["seq_hash"]) if isinstance(h, dict) else int(h)
                ws = self._workers_of.get(h)
                if ws is not None:
                    ws.discard(w)
                    if not ws:
                        del self._workers_of[h]
                if held is not None and h in held:
                    held.discard(h)
                    gone.append(h)
            if self._native is not None and gone:
                self._native.removed(w, gone)
        elif event.kind == "cleared":
            self.clear_worker(w)

    def remove_worker(self, worker: WorkerId) -> None:
        """Worker died (lease expired) — drop all its blocks
        (ref: indexer.rs:422)."""
        self.clear_worker(worker)

    def clear_worker(self, worker: WorkerId) -> None:
        for h in self._hashes_of.pop(worker, set()):
            ws = self._workers_of.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self._workers_of[h]
        if self._native is not None:
            self._native.clear_worker(worker)

    # -- matching (ref: indexer.rs:276 find_matches) --

    def find_matches(self, seq_hashes: Sequence[SequenceHash]) -> OverlapScores:
        """Count, per worker, how many *leading* blocks it holds.

        A worker's score only advances at block ``i`` if it matched all
        blocks before it — with chained hashes that is exactly the radix-walk
        the reference does.
        """
        if self._native is not None:
            return OverlapScores(
                scores=self._native.find_matches(list(seq_hashes))
            )
        scores: Dict[WorkerId, int] = {}
        for i, h in enumerate(seq_hashes):
            ws = self._workers_of.get(h)
            if not ws:
                break  # chained hashes: nobody can match deeper either
            advanced = False
            for w in ws:
                if scores.get(w, 0) == i:
                    scores[w] = i + 1
                    advanced = True
            if not advanced:
                break
        return OverlapScores(scores=scores)

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        return self.find_matches(
            compute_block_hashes_for_seq(list(tokens), self.block_size)
        )

    # -- introspection --

    def num_blocks(self, worker: Optional[WorkerId] = None) -> int:
        if worker is None:
            return len(self._workers_of)
        return len(self._hashes_of.get(worker, ()))

    def dump_events(self) -> List[RouterEvent]:
        """Serialise the index as stored-events (ref: indexer.rs:450) —
        the radix-snapshot payload for router replica warm-up."""
        out = []
        for w, hashes in self._hashes_of.items():
            if hashes:
                out.append(RouterEvent(
                    worker_id=w, kind="stored",
                    blocks=tuple({"seq_hash": h} for h in sorted(hashes)),
                ))
        return out


class ApproxKvIndexer:
    """TTL'd routing-decision history standing in for real KV events
    (ref: approx.rs:165).

    ``record_routing_decision`` notes that the chosen worker will soon hold
    the request's prefix blocks; entries expire after ``ttl_s`` (the horizon
    over which cached prefixes are presumed to survive engine eviction).
    """

    def __init__(self, block_size: int, ttl_s: float = 120.0):
        self.block_size = block_size
        self.ttl_s = ttl_s
        # (seq_hash, worker) -> expiry, insertion-ordered for cheap pruning
        self._entries: "OrderedDict[tuple, float]" = OrderedDict()
        self._workers_of: Dict[SequenceHash, Set[WorkerId]] = {}

    def record_routing_decision(
        self, worker: WorkerId, tokens: Sequence[int]
    ) -> None:
        now = time.monotonic()
        self._prune(now)
        for h in compute_block_hashes_for_seq(list(tokens), self.block_size):
            key = (h, worker)
            if key in self._entries:
                del self._entries[key]  # refresh recency
            else:
                self._workers_of.setdefault(h, set()).add(worker)
            self._entries[key] = now + self.ttl_s

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        self._prune(time.monotonic())
        scores: Dict[WorkerId, int] = {}
        hashes = compute_block_hashes_for_seq(list(tokens), self.block_size)
        for i, h in enumerate(hashes):
            ws = self._workers_of.get(h)
            if not ws:
                break
            advanced = False
            for w in ws:
                if scores.get(w, 0) == i:
                    scores[w] = i + 1
                    advanced = True
            if not advanced:
                break
        return OverlapScores(scores=scores)

    def remove_worker(self, worker: WorkerId) -> None:
        for (h, w) in [k for k in self._entries if k[1] == worker]:
            del self._entries[(h, w)]
            ws = self._workers_of.get(h)
            if ws is not None:
                ws.discard(w)
                if not ws:
                    del self._workers_of[h]

    def _prune(self, now: float) -> None:
        while self._entries:
            key, expiry = next(iter(self._entries.items()))
            if expiry > now:
                break
            del self._entries[key]
            h, w = key
            ws = self._workers_of.get(h)
            if ws is not None:
                ws.discard(w)
                if not ws:
                    del self._workers_of[h]
