"""Disaggregated prefill/decode serving
(ref: docs/architecture/disagg_serving.md; components/backends/vllm/src/
dynamo/vllm/handlers.py:89,207).

The decode worker orchestrates: it pre-allocates KV blocks, pushes a
bounded-prefill request to a prefill worker, receives the KV blocks over the
transfer plane into those pre-allocated slots, and resumes decoding from the
remotely-sampled first token. TPU-native data plane: jitted block
gather/scatter (``engine.model.make_kv_ops``) host-relayed over the TCP
transport; same-mesh transfers ride ICI through the identical jitted ops.

Fault model: reservations are epoch-guarded (stale transfers rejected
before write, see ``ici.StaleEpochError``), relay frames are
integrity-checked (``protocol.KvIntegrityError``), and repeated handoff
failures trip a breaker that flips decode to local-prefill for a cooldown
window. See README "Operations" for the full cascade.
"""

from .handlers import (
    DecodeHandler, DisaggConfig, PrefillHandler, PrefillQueueWorker,
)
from .ici import DevicePlane, StaleEpochError, default_plane
from .protocol import KvIntegrityError, kv_from_wire, kv_to_wire

__all__ = [
    "DecodeHandler",
    "DevicePlane",
    "DisaggConfig",
    "KvIntegrityError",
    "PrefillHandler",
    "PrefillQueueWorker",
    "StaleEpochError",
    "default_plane",
    "kv_from_wire",
    "kv_to_wire",
]
