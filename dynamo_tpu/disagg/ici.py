"""Device-native KV block transfer plane (the ICI data plane).

TPU-native replacement for the reference's NIXL GPU-to-GPU path plus its
CUDA layout-conversion kernels (ref: lib/llm/src/block_manager/
block_manager.rs:93-98 NIXL registration; lib/llm/src/kernels/
block_copy.cu:167-309 cross-TP reshape): paged KV blocks move prefill→decode
**device-to-device** with NO host numpy round-trip, and cross-TP layout
conversion falls out of sharding propagation instead of a hand-written
kernel.

Mechanism
---------
- Source side gathers the sequence's physical blocks with the jitted
  block-major gather (``engine.model.make_kv_ops``) — output stays ON
  DEVICE, sharded over the source mesh's ``tp`` axis.
- ``jax.device_put(gathered, NamedSharding(dst_mesh, …))`` moves the blocks
  straight into the destination mesh's layout. The runtime lowers this to
  direct device-to-device copies (ICI/DMA on TPU); when the prefill and
  decode engines run different TP degrees, the sharding change IS the
  resharding — XLA splits/merges the KV-head shards in flight, which is
  exactly what block_copy.cu does by hand.
- Destination side scatters into its pre-allocated block slots with the
  donated jitted scatter; pad rows land in physical block 0 (the trash
  block) by design.

Both jitted ops run on their engine's single step-executor thread — the
cache buffer is donated every step, so gather/scatter must serialise with
step execution (same discipline as ``InferenceEngine.extract_kv_blocks``).

Scope: engines in one process (multi-engine single host — e.g. P and D
sub-meshes of one chip pod slice). Cross-process transfers ride the host
relay (``disagg.protocol``) over DCN, as the reference does for
cross-node NIXL-less fallback.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

import jax
import numpy as np

from ..engine.engine import _pow2_bucket
from ..parallel.layout import kv_payload_shardings
from ..utils.logging import get_logger

log = get_logger("disagg.ici")


class StaleEpochError(RuntimeError):
    """The destination reservation was recycled (or resumed) before the
    transfer landed — writing now would corrupt another request's KV."""


class DevicePlane:
    """Process-local registry of engines addressable for device transfer.

    An engine registers under a plane id; a transfer between two registered
    engines is device-to-device. ``plane_id`` values are advertised in the
    ``kv_transfer`` control message next to the host-relay address, so a
    prefill worker sharing the process uses the device plane and any other
    worker falls back to the relay — mirroring the reference's
    NIXL-when-registered / bounce-buffer-otherwise split.
    """

    def __init__(self) -> None:
        self._engines: Dict[str, object] = {}

    def register(self, plane_id: str, engine) -> None:
        self._engines[plane_id] = engine

    def unregister(self, plane_id: str) -> None:
        self._engines.pop(plane_id, None)

    def get(self, plane_id: Optional[str]):
        if plane_id is None:
            return None
        return self._engines.get(plane_id)

    async def transfer(
        self, src_engine, src_block_ids, dst_engine, dst_block_ids,
        *, dst_seq_id: Optional[str] = None, dst_epoch: Optional[int] = None,
    ) -> int:
        """Move whole KV blocks src→dst on device. Returns bytes moved.

        Block id lists are padded to the same power of two: source pads
        gather the trash block, destination pads scatter back into the
        trash block, so no host-side slicing is ever needed.

        When ``dst_seq_id``/``dst_epoch`` are given, the destination
        reservation is re-validated *inside the scatter callable* — i.e. on
        the destination engine's executor thread, immediately before the
        donated write — and a stale epoch raises :class:`StaleEpochError`
        without touching the cache. This closes the query-then-write TOCTOU
        window a host-side liveness check leaves open.
        """
        n = len(src_block_ids)
        if len(dst_block_ids) != n:
            raise ValueError(
                f"block count mismatch: src {n} dst {len(dst_block_ids)}"
            )
        if n == 0:
            return 0
        m = _pow2_bucket(n)
        src_ids = np.zeros((m,), np.int32)
        src_ids[:n] = src_block_ids
        dst_ids = np.zeros((m,), np.int32)
        dst_ids[:n] = dst_block_ids

        src_loop = asyncio.get_running_loop()

        def _gather():
            return src_engine._kv_extract(src_engine.cache, src_ids)

        data = await src_loop.run_in_executor(src_engine._executor, _gather)

        if dst_engine is not src_engine:
            # the cross-mesh hop: device-to-device copy onto the layout's
            # [L, N, KV, bs, hd] transfer spec — KV heads over tp, the
            # same axis the destination cache shards, so the scatter
            # never reshards.  Quantized payloads carry the float32 scale
            # caches ("ks"/"vs") under their own scale spec.
            data = jax.device_put(
                data, kv_payload_shardings(dst_engine.mesh, data.keys()))

        def _scatter():
            if dst_epoch is not None and not dst_engine.reservation_valid(
                dst_seq_id, dst_epoch
            ):
                raise StaleEpochError(
                    f"reservation {dst_seq_id!r} epoch {dst_epoch} is stale"
                )
            dst_engine.cache = dst_engine._kv_inject(
                dst_engine.cache, dst_ids, data
            )

        await src_loop.run_in_executor(dst_engine._executor, _scatter)
        # every payload tensor counts: k + v (+ ks + vs scales), padded
        return sum(a.size * a.dtype.itemsize for a in data.values())


# A process-wide default plane: workers in one process (launcher-spawned
# P/D engine pairs) find each other without plumbing a registry handle.
default_plane = DevicePlane()
