"""Wire encoding for KV block payloads and transfer params
(ref: the ``kv_transfer_params`` dict threaded through handlers.py:147-188
and the block-ID-only descriptor design of disagg_serving.md §Efficient KV
Transfer — metadata rides the control message; bulk bytes ride the
transport's binary frames)."""

from __future__ import annotations

from typing import Dict

import numpy as np

try:  # bfloat16 numpy interop (jax dependency, always present with jax)
    import ml_dtypes

    _DTYPES = {"bfloat16": np.dtype(ml_dtypes.bfloat16)}
except Exception:  # pragma: no cover
    _DTYPES = {}


def _np_dtype(name: str) -> np.dtype:
    return _DTYPES.get(name, np.dtype(name))


def kv_to_wire(data: Dict[str, np.ndarray]) -> dict:
    """{"k","v"} arrays -> msgpack-safe dict (raw bytes + shape + dtype)."""
    k, v = data["k"], data["v"]
    return {
        "shape": list(k.shape),
        "dtype": k.dtype.name,
        "k": k.tobytes(),
        "v": v.tobytes(),
    }


def kv_from_wire(wire: dict) -> Dict[str, np.ndarray]:
    shape = tuple(wire["shape"])
    dt = _np_dtype(wire["dtype"])
    return {
        "k": np.frombuffer(wire["k"], dtype=dt).reshape(shape),
        "v": np.frombuffer(wire["v"], dtype=dt).reshape(shape),
    }
