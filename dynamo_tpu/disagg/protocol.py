"""Wire encoding for KV block payloads and transfer params
(ref: the ``kv_transfer_params`` dict threaded through handlers.py:147-188
and the block-ID-only descriptor design of disagg_serving.md §Efficient KV
Transfer — metadata rides the control message; bulk bytes ride the
transport's binary frames).

Every frame carries an integrity envelope: the byte length implied by
``shape``/``dtype`` plus a CRC32 over each tensor's raw bytes. The decode
side verifies the envelope *before* scattering into reserved blocks, so a
truncated, bit-flipped, or dtype-mangled relay payload is rejected (the
handoff falls back / retries) instead of poisoning the KV cache.

Quantized KV (``EngineConfig.kv_dtype`` int8/fp8) payloads carry two extra
tensors — the float32 per-(slot, head) scale caches ``ks``/``vs`` — with
their own shape/dtype/CRC entries in the same envelope, so dtype and
scales survive the handoff bit-exactly.  Frames without them decode to a
plain {"k", "v"} pair, keeping older bf16 peers interoperable.
"""

from __future__ import annotations

import zlib
from typing import Dict

import numpy as np

try:  # 1-byte-storage numpy interop (jax dependency, always present w/ jax)
    import ml_dtypes

    _DTYPES = {
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
        "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
    }
except Exception:  # pragma: no cover
    _DTYPES = {}


class KvIntegrityError(ValueError):
    """Wire payload failed its size/dtype/checksum verification."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return _DTYPES.get(name, np.dtype(name))
    except TypeError as exc:
        raise KvIntegrityError(f"unknown KV dtype {name!r}") from exc


def kv_to_wire(data: Dict[str, np.ndarray]) -> dict:
    """{"k","v"[,"ks","vs"]} arrays -> msgpack-safe dict (raw bytes +
    shape + dtype + per-tensor CRC32)."""
    k, v = data["k"], data["v"]
    kb, vb = k.tobytes(), v.tobytes()
    wire = {
        "shape": list(k.shape),
        "dtype": k.dtype.name,
        "k": kb,
        "v": vb,
        "k_crc": zlib.crc32(kb),
        "v_crc": zlib.crc32(vb),
    }
    if "ks" in data:
        ks, vs = data["ks"], data["vs"]
        ksb, vsb = ks.tobytes(), vs.tobytes()
        wire.update({
            "scale_shape": list(ks.shape),
            "scale_dtype": ks.dtype.name,
            "ks": ksb,
            "vs": vsb,
            "ks_crc": zlib.crc32(ksb),
            "vs_crc": zlib.crc32(vsb),
        })
    return wire


def _verify(name: str, buf: bytes, nbytes: int, crc) -> None:
    if len(buf) != nbytes:
        raise KvIntegrityError(
            f"{name} payload is {len(buf)} bytes, expected {nbytes}"
        )
    if crc is not None and zlib.crc32(buf) != int(crc):
        raise KvIntegrityError(f"{name} payload failed its checksum")


def kv_from_wire(wire: dict) -> Dict[str, np.ndarray]:
    """Decode and *verify* a wire frame. Raises :class:`KvIntegrityError`
    on truncation, checksum mismatch, or a dtype/shape that doesn't match
    the byte payload — never returns a partially-valid tensor set.

    Frames without ``k_crc``/``v_crc`` (older peers) still get the
    size check; the checksum is skipped.  Frames with ``ks``/``vs``
    (quantized KV) verify and return the scale tensors under the same
    contract.
    """
    shape = tuple(int(d) for d in wire["shape"])
    dt = _np_dtype(wire["dtype"])
    nbytes = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
    kb, vb = wire["k"], wire["v"]
    _verify("k", kb, nbytes, wire.get("k_crc"))
    _verify("v", vb, nbytes, wire.get("v_crc"))
    out = {
        "k": np.frombuffer(kb, dtype=dt).reshape(shape),
        "v": np.frombuffer(vb, dtype=dt).reshape(shape),
    }
    if "ks" in wire:
        s_shape = tuple(int(d) for d in wire["scale_shape"])
        s_dt = _np_dtype(wire["scale_dtype"])
        s_nbytes = int(np.prod(s_shape)) * s_dt.itemsize \
            if s_shape else s_dt.itemsize
        ksb, vsb = wire["ks"], wire["vs"]
        _verify("ks", ksb, s_nbytes, wire.get("ks_crc"))
        _verify("vs", vsb, s_nbytes, wire.get("vs_crc"))
        out["ks"] = np.frombuffer(ksb, dtype=s_dt).reshape(s_shape)
        out["vs"] = np.frombuffer(vsb, dtype=s_dt).reshape(s_shape)
    return out
