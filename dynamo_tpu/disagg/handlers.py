"""Decode/prefill worker handlers for disaggregated serving
(ref: components/backends/vllm/src/dynamo/vllm/handlers.py:89 Decode, :207
Prefill; conditional thresholds ref: lib/llm/src/disagg_router.rs:230).

Flow (decode-orchestrated, matching the reference):

  DecodeHandler.generate(request)
    ├─ below threshold / no prefill workers / pool full → local engine path
    ├─ reserve blocks on the decode engine
    ├─ push prefill request to a prefill worker (round-robin), carrying
    │  kv_transfer params {addr, request_id} — our kv_inject ingress addr
    ├─ PrefillHandler: engine.prefill_held → extract_kv → push blocks to
    │  decode's kv_inject endpoint → respond {token_id}
    ├─ inject arrives concurrently; decode awaits its completion event
    └─ engine.resume_prefilled(seq, first_token) → decode stream

The prefill worker *pushes* KV into pre-allocated decode blocks (the NIXL
write direction); bulk bytes ride the TCP transport's binary frames while
control messages carry only block metadata.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, Optional

import uuid

from ..engine.engine import EngineCore, InferenceEngine, Request
from ..runtime.component import Client
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..utils.logging import get_logger
from .ici import DevicePlane, default_plane
from .protocol import kv_from_wire, kv_to_wire

log = get_logger("disagg")


@dataclass
class DisaggConfig:
    """Conditional-disagg thresholds (ref: disagg_router.rs:230 — remote
    prefill only when the *new* work is long enough to be worth the
    transfer)."""

    min_remote_prefill_tokens: int = 32
    # refuse remote prefill when the decode pool is above this usage
    max_reserve_usage: float = 0.95
    # queue mode (ref: the JetStream pull-queue "Prefill Queue" in
    # docs/architecture/disagg_serving.md; nats.rs:426): decode workers
    # q_push prefill work onto the store work queue and prefill workers
    # q_pop it — slow prefill workers naturally take fewer items than fast
    # ones, and the queue depth is a direct backlog signal for the planner.
    # False = direct round-robin push (the legacy/fallback path).
    use_queue: bool = False
    queue_name: str = "prefill_queue"
    # how long decode waits for the queued prefill before falling back to
    # a local prefill
    queue_wait_s: float = 60.0


class PrefillHandler(AsyncEngine):
    """Prefill worker: bounded prefill + KV push-back
    (ref: handlers.py:207 PrefillWorkerHandler)."""

    def __init__(self, engine: InferenceEngine,
                 plane: Optional[DevicePlane] = None):
        self.engine = engine
        self.plane = plane if plane is not None else default_plane
        self.num_device_transfers = 0
        self.num_relay_transfers = 0

    async def _still_pending(self, xfer: Dict[str, Any]) -> bool:
        """Ask the decode worker whether the request is still waiting.

        The device-plane transfer writes straight into the reserved block
        ids, so a stale work item (decode timed out, blocks reallocated)
        would corrupt another request's KV. The query also marks the
        request transfer-in-flight on the decode side, so decode's timeout
        path waits for completion instead of freeing blocks mid-transfer.
        """
        try:
            transport = self.engine_runtime_transport(None)
            async for ack in transport.generate(
                xfer["addr"],
                {"request_id": xfer["request_id"], "query": True},
                Context(),
            ):
                return bool(ack.get("ok"))
        except Exception:
            log.exception("liveness query to decode failed")
        return False

    async def execute(
        self, request: Dict[str, Any], *, include_token: bool
    ) -> int:
        """Run one bounded prefill and push its KV into the decode worker's
        reserved blocks. Returns the first sampled token; with
        ``include_token`` the token rides the inject payload (queue mode has
        no response stream to carry it)."""
        xfer: Dict[str, Any] = request.get("kv_transfer") or {}
        req = Request(
            request_id=xfer.get("request_id") or f"prefill-{uuid.uuid4().hex}",
            token_ids=list(request["token_ids"]),
            max_tokens=1,
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
        )
        seq, first_token = await self.engine.prefill_held(req)
        dst_engine = self.plane.get(xfer.get("plane_id"))
        dst_ids = list(xfer.get("block_ids") or [])
        if (dst_engine is not None and dst_ids and include_token
                and not await self._still_pending(xfer)):
            # queue mode: the item may be stale (decode gave up and its
            # reserved blocks were recycled) — never write into them
            self.engine.release_held(seq)
            raise RuntimeError("decode no longer waiting — dropping item")
        if dst_engine is not None and dst_ids:
            # device plane: blocks move src→dst on device (ICI), control
            # message carries only the completion flag — the reference's
            # "messages carry only block IDs" design taken to its limit
            try:
                if len(seq.block_table) < len(dst_ids):
                    raise RuntimeError(
                        f"held {len(seq.block_table)} blocks < "
                        f"{len(dst_ids)} reserved"
                    )
                await self.plane.transfer(
                    self.engine, list(seq.block_table)[: len(dst_ids)],
                    dst_engine, dst_ids,
                )
            finally:
                self.engine.release_held(seq)
            self.num_device_transfers += 1
            payload: Dict[str, Any] = {"device_done": True}
        else:
            try:
                data = await self.engine.extract_kv(seq)
            finally:
                self.engine.release_held(seq)
            self.num_relay_transfers += 1
            payload = kv_to_wire(data)
        payload["request_id"] = xfer["request_id"]
        if include_token:
            payload["first_token"] = first_token
        # push the blocks into the decode worker's pre-allocated slots
        transport = self.engine_runtime_transport(None)
        async for ack in transport.generate(xfer["addr"], payload, Context()):
            if not ack.get("ok", False):
                raise RuntimeError(f"kv inject rejected: {ack}")
        return first_token

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        request = dict(request)
        xfer = dict(request.get("kv_transfer") or {})
        xfer.setdefault("request_id", context.id)
        request["kv_transfer"] = xfer
        first_token = await self.execute(request, include_token=False)
        yield {"token_ids": [first_token], "finished": True,
               "finish_reason": "remote_prefill"}

    # seam for tests / runtime injection
    def engine_runtime_transport(self, context: Optional[Context]):
        from ..runtime.transport import TransportClient

        if not hasattr(self, "_transport"):
            self._transport = TransportClient()
        return self._transport


class PrefillQueueWorker:
    """Pull-mode prefill consumer (ref: the JetStream prefill queue,
    lib/runtime/src/transports/nats.rs:426): pops work items from the store
    work queue and executes them via :class:`PrefillHandler`. A worker only
    takes what it can chew (``max_inflight``), so heterogeneous prefill
    workers self-balance and the queue length is the backlog signal.
    On failure it reports the error to the decode worker's inject endpoint
    so decode falls back to local prefill immediately instead of timing
    out."""

    def __init__(self, handler: PrefillHandler, store,
                 queue_name: str = "prefill_queue", max_inflight: int = 2):
        self.handler = handler
        self.store = store
        self.queue_name = queue_name
        self.max_inflight = max_inflight
        self.num_pulled = 0
        self.num_failed = 0
        self.num_expired = 0
        self._task: Optional[asyncio.Task] = None
        self._inflight: set = set()

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._pull_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for t in list(self._inflight):
            t.cancel()

    async def _pull_loop(self) -> None:
        import msgpack

        sem = asyncio.Semaphore(self.max_inflight)
        while True:
            await sem.acquire()
            try:
                raw = await self.store.q_pop(self.queue_name, timeout_s=30.0)
            except Exception:
                sem.release()
                log.exception("prefill queue pop failed — retrying")
                await asyncio.sleep(0.5)
                continue
            if raw is None:
                sem.release()
                continue
            try:
                item = msgpack.unpackb(raw, raw=False)
            except Exception:
                sem.release()
                log.exception("bad prefill queue item — dropping")
                continue
            deadline = item.get("queue_deadline")
            if deadline is not None and time.time() > float(deadline):
                # decode already gave up on this item — don't prefill into
                # block ids that may have been recycled
                sem.release()
                self.num_expired += 1
                log.warning("dropping expired prefill item %s",
                            (item.get("kv_transfer") or {}).get("request_id"))
                continue
            task = asyncio.create_task(self._run_one(item, sem))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)

    async def _run_one(self, item: dict, sem: asyncio.Semaphore) -> None:
        try:
            self.num_pulled += 1
            await self.handler.execute(item, include_token=True)
        except Exception as exc:
            self.num_failed += 1
            log.exception("queued prefill failed — notifying decode")
            await self._report_failure(item, exc)
        finally:
            sem.release()

    async def _report_failure(self, item: dict, exc: Exception) -> None:
        xfer = item.get("kv_transfer") or {}
        addr, rid = xfer.get("addr"), xfer.get("request_id")
        if not addr or not rid:
            return
        try:
            transport = self.handler.engine_runtime_transport(None)
            async for _ in transport.generate(
                addr, {"request_id": rid, "error": str(exc)}, Context()
            ):
                break
        except Exception:
            log.exception("failure report to decode failed")


class KvInjectHandler(AsyncEngine):
    """Decode-worker ingress for pushed KV blocks: scatters the payload
    into the reserved sequence's blocks and signals the waiting decode
    handler."""

    def __init__(self, decode: "DecodeHandler"):
        self.decode = decode

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        rid = request["request_id"]
        pending = self.decode.pending.get(rid)
        if pending is None:
            yield {"ok": False, "error": f"unknown request {rid}"}
            return
        seq, done = pending
        if request.get("query"):
            # prefill worker asking "still waiting?" before a device-plane
            # write; marking in-flight makes decode's timeout path wait for
            # the transfer instead of freeing the target blocks under it
            self.decode.inflight.add(rid)
            yield {"ok": True}
            return
        if request.get("error"):
            # queue-mode prefill worker reporting failure: wake the waiting
            # decode handler so it falls back to local prefill immediately
            if not done.done():
                done.set_exception(RuntimeError(
                    f"remote prefill failed: {request['error']}"
                ))
            yield {"ok": True}
            return
        # queue mode has no response stream — the first token rides here
        result = request.get("first_token", True)
        if request.get("device_done"):
            # blocks already arrived over the device plane — this is just
            # the completion signal
            if not done.done():
                done.set_result(result)
            yield {"ok": True}
            return
        try:
            await self.decode.engine.inject_kv(seq, kv_from_wire(request))
        except Exception as exc:
            if not done.done():
                done.set_exception(exc)
            yield {"ok": False, "error": str(exc)}
            return
        if not done.done():
            done.set_result(result)
        yield {"ok": True}


class DecodeHandler(AsyncEngine):
    """Decode worker: conditional remote prefill + resume
    (ref: handlers.py:89 DecodeWorkerHandler)."""

    def __init__(
        self,
        engine: InferenceEngine,
        prefill_client: Optional[Client] = None,
        config: Optional[DisaggConfig] = None,
        plane: Optional[DevicePlane] = None,
        store=None,
    ):
        self.engine = engine
        self.prefill_client = prefill_client
        self.config = config or DisaggConfig()
        self.store = store  # required for queue mode (use_queue)
        # request_id -> (reserved seq, inject-complete future)
        self.pending: Dict[str, tuple] = {}
        # request ids with a device-plane transfer in flight (the prefill
        # worker's liveness query marks these; our timeout path then grants
        # a grace period instead of freeing blocks mid-write)
        self.inflight: set = set()
        self._depth_task: Optional[asyncio.Task] = None
        self.kv_inject_addr: Optional[str] = None  # set after serving
        self.num_remote_prefills = 0
        self.num_local_prefills = 0
        # backlog signal for the planner, refreshed on every enqueue
        # (published via WorkerMetricsPublisher extra_fn)
        self.last_queue_depth = 0
        # advertise this engine on the device plane so a same-process
        # prefill worker transfers KV device-to-device instead of relaying
        self.plane = plane if plane is not None else default_plane
        self.plane_id: Optional[str] = None
        if hasattr(engine, "mesh"):  # device engines only (not mocker)
            self.plane_id = uuid.uuid4().hex
            self.plane.register(self.plane_id, engine)

    def close(self) -> None:
        """Drop the device-plane registration (the registry would otherwise
        pin the engine — and its KV cache — for the process lifetime)."""
        if self.plane_id is not None:
            self.plane.unregister(self.plane_id)
            self.plane_id = None
        if self._depth_task is not None:
            self._depth_task.cancel()
            self._depth_task = None

    def inject_handler(self) -> KvInjectHandler:
        return KvInjectHandler(self)

    def _should_remote_prefill(self, token_ids: list) -> bool:
        if self.kv_inject_addr is None:
            return False
        if self.config.use_queue:
            if self.store is None:
                return False
            # with zero live prefill workers nobody will ever pop the
            # queue — go local immediately rather than stalling every
            # long prompt for queue_wait_s (the client is optional so
            # store-only test rigs still work)
            if (self.prefill_client is not None
                    and not self.prefill_client.instance_ids()):
                return False
        else:
            if (self.prefill_client is None
                    or not self.prefill_client.instance_ids()):
                return False
        if len(token_ids) < self.config.min_remote_prefill_tokens:
            return False
        if self.engine.stats.kv_usage > self.config.max_reserve_usage:
            return False
        return True

    def metrics_extra(self) -> dict:
        """Merged into the worker's load-metrics snapshot (planner input)."""
        return {"prefill_queue_depth": self.last_queue_depth}

    def start_depth_monitor(self, interval_s: float = 1.0) -> None:
        """Keep ``last_queue_depth`` fresh even when no pushes happen —
        a metric sampled only at enqueue time would report phantom backlog
        forever after a burst drains."""
        if self._depth_task is None and self.store is not None:
            self._depth_task = asyncio.create_task(
                self._depth_loop(interval_s)
            )

    async def _depth_loop(self, interval_s: float) -> None:
        while True:
            try:
                self.last_queue_depth = await self.store.q_len(
                    self.config.queue_name
                )
            except Exception:
                pass
            await asyncio.sleep(interval_s)

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        token_ids = list(request["token_ids"])
        if request.get("mm"):
            # multimodal prompts prefill locally: the remote prefill path
            # would need the embeddings shipped and spliced on the prefill
            # worker (future work); local keeps EPD correctness
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return
        if not self._should_remote_prefill(token_ids):
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return

        req = Request(
            request_id=context.id,
            token_ids=token_ids,
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
            eos_token_ids=tuple(request.get("eos_token_ids", ())),
            ignore_eos=bool(request.get("ignore_eos", False)),
        )
        seq = self.engine.reserve_sequence(req)
        if seq is None:  # pool can't host it — prefill locally instead
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return

        done: asyncio.Future = asyncio.get_running_loop().create_future()
        self.pending[context.id] = (seq, done)
        try:
            prefill_request = {
                "token_ids": token_ids,
                "temperature": req.temperature,
                "top_k": req.top_k,
                "top_p": req.top_p,
                "seed": req.seed,
                "kv_transfer": {
                    "request_id": context.id,
                    "addr": self.kv_inject_addr,
                    "plane_id": self.plane_id,
                    "block_ids": list(seq.block_table),
                },
            }
            first_token: Optional[int] = None
            if self.config.use_queue:
                # queue mode: enqueue and wait — the inject payload carries
                # the first token (or the failure) back to us
                import msgpack

                prefill_request["queue_deadline"] = (
                    time.time() + self.config.queue_wait_s
                )
                await self.store.q_push(
                    self.config.queue_name, msgpack.packb(prefill_request)
                )
                try:
                    self.last_queue_depth = await self.store.q_len(
                        self.config.queue_name
                    )
                except Exception:
                    pass
                try:
                    result = await asyncio.wait_for(
                        done, timeout=self.config.queue_wait_s
                    )
                except asyncio.TimeoutError:
                    if context.id not in self.inflight:
                        raise
                    # a device-plane transfer is mid-write into our
                    # reserved blocks — freeing them now would hand
                    # corrupted blocks to the next request; grant a grace
                    # window for the transfer to land
                    result = await asyncio.wait_for(done, timeout=30.0)
                # bool is an int subclass — require a real token id, not
                # the legacy True completion marker
                if type(result) is not int:
                    raise RuntimeError(
                        "queued prefill completed without a first token"
                    )
                first_token = result
            else:
                async for item in self.prefill_client.round_robin(
                    prefill_request, context
                ):
                    first_token = item["token_ids"][0]
                if first_token is None:
                    raise RuntimeError("prefill worker returned no token")
                await asyncio.wait_for(done, timeout=120.0)
            self.num_remote_prefills += 1
            log.debug("remote prefill complete: %s (%d tokens)",
                      context.id, len(token_ids))
        except Exception:
            # remote prefill failed — fall back to local so the request
            # still completes (the Migration operator retries above us for
            # stream-level failures)
            log.exception("remote prefill failed — falling back to local")
            self.engine.cancel_reservation(seq)
            self.pending.pop(context.id, None)
            self.inflight.discard(context.id)
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return
        finally:
            self.pending.pop(context.id, None)
            self.inflight.discard(context.id)

        async def _on_stop() -> None:
            await context.wait_stopped()
            self.engine.abort(req.request_id,
                              "killed" if context.is_killed() else "cancelled")

        watcher = asyncio.create_task(_on_stop())
        try:
            async for out in self.engine.resume_prefilled(seq, first_token):
                if context.is_killed():
                    return
                yield {
                    "token_ids": [out.token_id],
                    "index": out.index,
                    "finished": out.finished,
                    "finish_reason": out.finish_reason,
                    "num_prompt_tokens": out.num_prompt_tokens,
                }
                if out.finished:
                    return
            # engine path exhausted without a finished marker (abort):
            # nothing further to yield
        finally:
            watcher.cancel()
