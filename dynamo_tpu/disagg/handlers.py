"""Decode/prefill worker handlers for disaggregated serving
(ref: components/backends/vllm/src/dynamo/vllm/handlers.py:89 Decode, :207
Prefill; conditional thresholds ref: lib/llm/src/disagg_router.rs:230).

Flow (decode-orchestrated, matching the reference):

  DecodeHandler.generate(request)
    ├─ below threshold / no prefill workers / pool full → local engine path
    ├─ reserve blocks on the decode engine (epoch-stamped)
    ├─ push prefill request to a prefill worker (round-robin), carrying
    │  kv_transfer params {addr, request_id, epoch, deadline} — our
    │  kv_inject ingress addr
    ├─ PrefillHandler: engine.prefill_held → extract_kv → push blocks to
    │  decode's kv_inject endpoint → respond {token_id}
    ├─ inject arrives concurrently; decode awaits its completion event
    └─ engine.resume_prefilled(seq, first_token) → decode stream

The prefill worker *pushes* KV into pre-allocated decode blocks (the NIXL
write direction); bulk bytes ride the TCP transport's binary frames while
control messages carry only block metadata.

Fault model (see README "Operations"):

- every reservation carries an epoch; both the device-plane scatter and
  the wire-relay inject validate epoch-before-write, so a delayed
  transfer aimed at a recycled reservation is rejected, never scattered;
- relay frames are integrity-checked (``protocol.KvIntegrityError``) —
  corrupt/truncated payloads are rejected and retried, not injected;
- the push is retried with exponential backoff inside the request's
  remaining deadline budget; per-prefill-worker failures feed circuit
  breakers, and repeated handoff failures flip the decode handler to
  local-prefill for a cooldown window (DynaServe-style unified fallback);
- orphan sweepers reap deadline-expired pending handoffs and held
  prefill sequences so a crashed peer never pins KV blocks forever.

Injectable fault sites: ``disagg.prefill``, ``disagg.transfer``,
``disagg.inject`` (see runtime/faults.py).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

import uuid

from ..engine.engine import EngineCore, InferenceEngine, Request
from ..runtime import faults
from ..runtime.circuit import (
    OPEN, BreakerConfig, CircuitBreaker, CircuitBreakerRegistry,
)
from ..runtime.component import Client
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..tracing import get_tracer, trace_span
from ..utils.logging import TraceContext, get_logger
from .ici import DevicePlane, StaleEpochError, default_plane
from .protocol import KvIntegrityError, kv_from_wire, kv_to_wire

log = get_logger("disagg")


class PermanentHandoffError(RuntimeError):
    """The decode side rejected the handoff for good (stale epoch, unknown
    request) — retrying the push cannot succeed."""


@dataclass
class DisaggConfig:
    """Conditional-disagg thresholds (ref: disagg_router.rs:230 — remote
    prefill only when the *new* work is long enough to be worth the
    transfer) plus the handoff fault-tolerance knobs.

    Every ``*_s``/retry/breaker field is plumbed from ``RuntimeConfig``
    (``DYNTPU_DISAGG_*`` env) via :meth:`from_runtime`."""

    min_remote_prefill_tokens: int = 32
    # refuse remote prefill when the decode pool is above this usage
    max_reserve_usage: float = 0.95
    # queue mode (ref: the JetStream pull-queue "Prefill Queue" in
    # docs/architecture/disagg_serving.md; nats.rs:426): decode workers
    # q_push prefill work onto the store work queue and prefill workers
    # q_pop it — slow prefill workers naturally take fewer items than fast
    # ones, and the queue depth is a direct backlog signal for the planner.
    # False = direct round-robin push (the legacy/fallback path).
    use_queue: bool = False
    queue_name: str = "prefill_queue"
    # how long decode waits for the queued prefill before falling back to
    # a local prefill
    queue_wait_s: float = 60.0
    # total wall budget for one handoff (reserve → inject complete); the
    # request's own remaining deadline caps it further
    handoff_timeout_s: float = 120.0
    # extra wait granted when a device-plane transfer is already mid-write
    # into our reserved blocks at timeout (freeing them would corrupt)
    inflight_grace_s: float = 30.0
    # per-attempt cap on one KV push (device transfer or relay inject ack)
    inject_timeout_s: float = 10.0
    # transfer retries after the first attempt, exponential backoff,
    # always bounded by the remaining handoff deadline
    transfer_max_retries: int = 2
    retry_backoff_base_s: float = 0.05
    # handoff-failure breaker: this many consecutive remote-prefill
    # failures flip the decode handler to local prefill for the cooldown
    breaker_failure_threshold: int = 3
    breaker_cooldown_s: float = 10.0
    # orphan GC cadence and how far past its deadline an entry must be
    orphan_sweep_interval_s: float = 5.0
    orphan_grace_s: float = 5.0

    @classmethod
    def from_runtime(cls, rc, **overrides) -> "DisaggConfig":
        """Build from a ``RuntimeConfig`` (``DYNTPU_DISAGG_*`` env knobs),
        with explicit keyword overrides winning."""
        cfg = cls(
            queue_wait_s=rc.disagg_queue_wait_s,
            handoff_timeout_s=rc.disagg_handoff_timeout_s,
            inflight_grace_s=rc.disagg_inflight_grace_s,
            inject_timeout_s=rc.disagg_inject_timeout_s,
            transfer_max_retries=rc.disagg_transfer_max_retries,
            retry_backoff_base_s=rc.disagg_retry_backoff_base_s,
            breaker_failure_threshold=rc.disagg_breaker_failure_threshold,
            breaker_cooldown_s=rc.disagg_breaker_cooldown_s,
            orphan_sweep_interval_s=rc.disagg_orphan_sweep_interval_s,
            orphan_grace_s=rc.disagg_orphan_grace_s,
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    def breaker_config(self) -> BreakerConfig:
        return BreakerConfig(
            failure_threshold=self.breaker_failure_threshold,
            open_timeout_s=self.breaker_cooldown_s,
        )


@dataclass
class PendingHandoff:
    """Decode-side state of one in-flight handoff."""

    seq: Any
    done: asyncio.Future
    epoch: int
    # monotonic instant after which the orphan sweeper may reap this entry
    deadline: float


class PrefillHandler(AsyncEngine):
    """Prefill worker: bounded prefill + KV push-back
    (ref: handlers.py:207 PrefillWorkerHandler)."""

    def __init__(self, engine: InferenceEngine,
                 plane: Optional[DevicePlane] = None,
                 config: Optional[DisaggConfig] = None):
        self.engine = engine
        self.plane = plane if plane is not None else default_plane
        self.config = config or DisaggConfig()
        self.num_device_transfers = 0
        self.num_relay_transfers = 0
        self.num_transfer_retries = 0
        self.num_orphans_reaped = 0
        # rid -> (held seq, monotonic reap deadline): KV awaiting push;
        # the orphan sweeper releases entries whose decode peer vanished
        self._held: Dict[str, tuple] = {}
        self._sweep_task: Optional[asyncio.Task] = None

    def metrics_extra(self) -> dict:
        """Merged into the worker's load-metrics snapshot."""
        return {"disagg": {
            "transfer_retries_total": float(self.num_transfer_retries),
            "orphans_reaped_total": float(self.num_orphans_reaped),
        }}

    # ----------------------- orphan GC ---------------------------------

    def start_orphan_sweeper(self) -> None:
        if self._sweep_task is None:
            from ..runtime.tasks import spawn_logged

            self._sweep_task = spawn_logged(
                self._sweep_loop(), name="disagg-prefill-sweep"
            )

    def close(self) -> None:
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            self._sweep_task = None

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.orphan_sweep_interval_s)
            self.sweep_orphans()

    def sweep_orphans(self) -> int:
        """Release held sequences whose handoff deadline long passed —
        the decode peer crashed or gave up; its epoch guard makes a
        late write impossible anyway, so pinning the blocks helps nobody."""
        now = time.monotonic()
        reaped = 0
        for rid, (seq, deadline) in list(self._held.items()):
            if now <= deadline + self.config.orphan_grace_s:
                continue
            if self._held.pop(rid, None) is None:
                continue
            self.engine.release_held(seq)
            self.num_orphans_reaped += 1
            reaped += 1
            log.warning("reaped orphaned held prefill %s", rid)
        return reaped

    # ----------------------- handoff -----------------------------------

    async def _still_pending(self, xfer: Dict[str, Any]) -> bool:
        """Ask the decode worker whether the request is still waiting.

        The epoch guard (validated again inside the scatter) is what makes
        a stale write *impossible*; this query is the cheap early-out for
        queue items decode already gave up on, and it marks the request
        transfer-in-flight so decode's timeout path waits for completion
        instead of freeing blocks mid-transfer.
        """
        try:
            transport = self.engine_runtime_transport(None)
            async for ack in transport.generate(
                xfer["addr"],
                {"request_id": xfer["request_id"], "query": True},
                Context(),
            ):
                return bool(ack.get("ok"))
        except Exception:
            log.exception("liveness query to decode failed")
        return False

    async def execute(
        self, request: Dict[str, Any], *, include_token: bool
    ) -> int:
        """Run one bounded prefill and push its KV into the decode worker's
        reserved blocks. Returns the first sampled token; with
        ``include_token`` the token rides the inject payload (queue mode has
        no response stream to carry it).

        The push is attempted up to ``1 + transfer_max_retries`` times with
        exponential backoff, each attempt capped by ``inject_timeout_s``
        and the whole loop by the handoff deadline the decode side stamped
        into the transfer params (wall clock — it crosses processes)."""
        xfer: Dict[str, Any] = request.get("kv_transfer") or {}
        rid = xfer.get("request_id") or f"prefill-{uuid.uuid4().hex}"
        rule = await faults.maybe_delay(faults.active("disagg.prefill", rid))
        if rule is not None and rule.kind != faults.DELAY:
            raise RuntimeError(
                f"injected disagg.prefill fault ({rule.kind})"
            )
        deadline = xfer.get("deadline")  # wall clock, stamped by decode

        def _remaining() -> Optional[float]:
            return None if deadline is None else float(deadline) - time.time()

        trace = None
        if xfer.get("traceparent"):
            trace = TraceContext.parse(xfer["traceparent"])
        span_ctx = Context(request_id=rid, trace=trace)

        req = Request(
            request_id=rid,
            token_ids=list(request["token_ids"]),
            max_tokens=1,
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
        )
        with trace_span("disagg.prefill", span_ctx,
                        attrs={"request_id": rid,
                               "prompt_tokens": len(req.token_ids)}):
            seq, first_token = await self.engine.prefill_held(req)
        hold_budget = _remaining()
        if hold_budget is None or hold_budget < 0:
            hold_budget = self.config.handoff_timeout_s
        self._held[rid] = (seq, time.monotonic() + hold_budget)
        try:
            dst_engine = self.plane.get(xfer.get("plane_id"))
            dst_ids = list(xfer.get("block_ids") or [])
            use_device = dst_engine is not None and bool(dst_ids)
            if (use_device and include_token
                    and not await self._still_pending(xfer)):
                # queue mode: the item may be stale (decode gave up and its
                # reserved blocks were recycled) — don't bother prefetching
                # a transfer the epoch guard would reject anyway
                raise PermanentHandoffError(
                    "decode no longer waiting — dropping item"
                )
            if use_device and len(seq.block_table) < len(dst_ids):
                raise PermanentHandoffError(
                    f"held {len(seq.block_table)} blocks < "
                    f"{len(dst_ids)} reserved"
                )
            data = None
            if not use_device:
                data = await self.engine.extract_kv(seq)
            await self._push_with_retry(
                xfer, rid, seq, dst_engine if use_device else None, dst_ids,
                data, first_token, include_token, _remaining, span_ctx,
            )
            if use_device:
                self.num_device_transfers += 1
            else:
                self.num_relay_transfers += 1
        finally:
            if self._held.pop(rid, None) is not None:
                self.engine.release_held(seq)
        return first_token

    async def _push_with_retry(
        self, xfer, rid, seq, dst_engine, dst_ids, data, first_token,
        include_token, remaining, span_ctx,
    ) -> None:
        attempts = 1 + max(0, self.config.transfer_max_retries)
        last_exc: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                self.num_transfer_retries += 1
                backoff = self.config.retry_backoff_base_s * (
                    2 ** (attempt - 1)
                )
                rem = remaining()
                if rem is not None:
                    if rem <= 0:
                        break
                    backoff = min(backoff, rem)
                await asyncio.sleep(backoff)
            timeout = self.config.inject_timeout_s
            rem = remaining()
            if rem is not None:
                if rem <= 0:
                    break
                timeout = min(timeout, rem)
            try:
                with trace_span(
                    "disagg.transfer", span_ctx,
                    attrs={"request_id": rid, "attempt": attempt,
                           "path": "device" if dst_engine else "relay"},
                ):
                    await self._push_once(
                        xfer, rid, seq, dst_engine, dst_ids, data,
                        first_token, include_token, timeout,
                    )
                return
            except (StaleEpochError, PermanentHandoffError):
                raise
            except Exception as exc:
                last_exc = exc
                log.warning("kv push attempt %d/%d for %s failed: %r",
                            attempt + 1, attempts, rid, exc)
        raise last_exc if last_exc is not None else TimeoutError(
            f"handoff deadline exhausted for {rid}"
        )

    async def _push_once(
        self, xfer, rid, seq, dst_engine, dst_ids, data, first_token,
        include_token, timeout,
    ) -> None:
        rule = await faults.maybe_delay(faults.active("disagg.transfer", rid))
        corrupt = rule is not None and rule.kind == faults.TRUNCATE
        if rule is not None and rule.kind not in (faults.DELAY,
                                                  faults.TRUNCATE):
            raise RuntimeError(
                f"injected disagg.transfer fault ({rule.kind})"
            )
        epoch = xfer.get("epoch")
        if dst_engine is not None:
            if corrupt:
                # device transfers are atomic (one scatter) — a truncation
                # can only manifest as a failed attempt
                raise RuntimeError("injected disagg.transfer truncate")
            await asyncio.wait_for(
                self.plane.transfer(
                    self.engine, list(seq.block_table)[: len(dst_ids)],
                    dst_engine, dst_ids,
                    dst_seq_id=rid, dst_epoch=epoch,
                ),
                timeout=timeout,
            )
            payload: Dict[str, Any] = {"device_done": True}
        else:
            payload = kv_to_wire(data)
            if corrupt:
                # chop the frame mid-tensor: the decode-side integrity
                # check must reject it before anything touches the cache
                payload["k"] = payload["k"][: len(payload["k"]) // 2]
        payload["request_id"] = rid
        if epoch is not None:
            payload["epoch"] = epoch
        if include_token:
            payload["first_token"] = first_token
        transport = self.engine_runtime_transport(None)

        async def _push() -> None:
            async for ack in transport.generate(
                xfer["addr"], payload, Context()
            ):
                if not ack.get("ok", False):
                    if ack.get("permanent"):
                        raise PermanentHandoffError(
                            f"kv inject rejected: {ack}"
                        )
                    raise RuntimeError(f"kv inject rejected: {ack}")

        await asyncio.wait_for(_push(), timeout=timeout)

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        request = dict(request)
        xfer = dict(request.get("kv_transfer") or {})
        xfer.setdefault("request_id", context.id)
        request["kv_transfer"] = xfer
        first_token = await self.execute(request, include_token=False)
        yield {"token_ids": [first_token], "finished": True,
               "finish_reason": "remote_prefill"}

    # seam for tests / runtime injection
    def engine_runtime_transport(self, context: Optional[Context]):
        from ..runtime.transport import TransportClient

        if not hasattr(self, "_transport"):
            self._transport = TransportClient()
        return self._transport


class PrefillQueueWorker:
    """Pull-mode prefill consumer (ref: the JetStream prefill queue,
    lib/runtime/src/transports/nats.rs:426): pops work items from the store
    work queue and executes them via :class:`PrefillHandler`. A worker only
    takes what it can chew (``max_inflight``), so heterogeneous prefill
    workers self-balance and the queue length is the backlog signal.
    On failure it reports the error to the decode worker's inject endpoint
    so decode falls back to local prefill immediately instead of timing
    out."""

    def __init__(self, handler: PrefillHandler, store,
                 queue_name: str = "prefill_queue", max_inflight: int = 2):
        self.handler = handler
        self.store = store
        self.queue_name = queue_name
        self.max_inflight = max_inflight
        self.num_pulled = 0
        self.num_failed = 0
        self.num_expired = 0
        self._task: Optional[asyncio.Task] = None
        self._inflight: set = set()

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._pull_loop())

    async def stop(self) -> None:
        tasks: List[asyncio.Task] = list(self._inflight)
        if self._task is not None:
            tasks.append(self._task)
            self._task = None
        for t in tasks:
            t.cancel()
        if tasks:
            # await the cancellations: leaving them mid-flight leaks tasks
            # and races test teardown (a cancelled _run_one may still be
            # touching the engine)
            await asyncio.gather(*tasks, return_exceptions=True)
        self._inflight.clear()

    async def _pull_loop(self) -> None:
        import msgpack

        sem = asyncio.Semaphore(self.max_inflight)
        while True:
            await sem.acquire()
            try:
                raw = await self.store.q_pop(self.queue_name, timeout_s=30.0)
            except Exception:
                sem.release()
                log.exception("prefill queue pop failed — retrying")
                await asyncio.sleep(0.5)
                continue
            if raw is None:
                sem.release()
                continue
            try:
                item = msgpack.unpackb(raw, raw=False)
            except Exception:
                sem.release()
                log.exception("bad prefill queue item — dropping")
                continue
            deadline = item.get("queue_deadline")
            if deadline is not None and time.time() > float(deadline):
                # decode already gave up on this item — don't prefill into
                # block ids that may have been recycled
                sem.release()
                self.num_expired += 1
                log.warning("dropping expired prefill item %s",
                            (item.get("kv_transfer") or {}).get("request_id"))
                continue
            task = asyncio.create_task(self._run_one(item, sem))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)

    async def _run_one(self, item: dict, sem: asyncio.Semaphore) -> None:
        try:
            self.num_pulled += 1
            await self.handler.execute(item, include_token=True)
        except Exception as exc:
            self.num_failed += 1
            log.exception("queued prefill failed — notifying decode")
            await self._report_failure(item, exc)
        finally:
            sem.release()

    async def _report_failure(self, item: dict, exc: Exception) -> None:
        xfer = item.get("kv_transfer") or {}
        addr, rid = xfer.get("addr"), xfer.get("request_id")
        if not addr or not rid:
            return
        try:
            transport = self.handler.engine_runtime_transport(None)
            async for _ in transport.generate(
                addr, {"request_id": rid, "error": str(exc)}, Context()
            ):
                break
        except Exception:
            log.exception("failure report to decode failed")


class KvInjectHandler(AsyncEngine):
    """Decode-worker ingress for pushed KV blocks: verifies the frame's
    epoch + integrity envelope, scatters the payload into the reserved
    sequence's blocks, and signals the waiting decode handler. Rejections
    are answered, never raised — the prefill side decides whether the
    failure is retryable (``permanent`` flag)."""

    def __init__(self, decode: "DecodeHandler"):
        self.decode = decode

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        rid = request["request_id"]
        rule = await faults.maybe_delay(faults.active("disagg.inject", rid))
        if rule is not None and rule.kind != faults.DELAY:
            yield {"ok": False,
                   "error": f"injected disagg.inject fault ({rule.kind})"}
            return
        pending = self.decode.pending.get(rid)
        if pending is None:
            yield {"ok": False, "error": f"unknown request {rid}",
                   "permanent": True}
            return
        epoch = request.get("epoch")
        if epoch is not None and (
            int(epoch) != pending.epoch
            or not self.decode.engine.reservation_valid(rid, int(epoch))
        ):
            # the reservation these bytes were aimed at no longer exists —
            # rejecting here (and again inside the scatter) is what turns
            # the recycled-block corruption race into a clean refusal
            self.decode.num_epoch_rejects += 1
            yield {"ok": False, "error": f"stale epoch {epoch} for {rid}",
                   "permanent": True}
            return
        if request.get("query"):
            # prefill worker asking "still waiting?" before a device-plane
            # write; marking in-flight makes decode's timeout path wait for
            # the transfer instead of freeing the target blocks under it
            self.decode.inflight.add(rid)
            yield {"ok": True}
            return
        if request.get("error"):
            # queue-mode prefill worker reporting failure: wake the waiting
            # decode handler so it falls back to local prefill immediately
            if not pending.done.done():
                pending.done.set_exception(RuntimeError(
                    f"remote prefill failed: {request['error']}"
                ))
            yield {"ok": True}
            return
        # queue mode has no response stream — the first token rides here
        result = request.get("first_token", True)
        if request.get("device_done"):
            # blocks already arrived over the device plane — this is just
            # the completion signal
            if not pending.done.done():
                pending.done.set_result(result)
            yield {"ok": True}
            return
        t0 = time.monotonic()
        try:
            data = kv_from_wire(request)
        except KvIntegrityError as exc:
            # corrupt/truncated frame: refuse before anything touches the
            # cache; the prefill side re-sends (per-attempt fault), so
            # this is retryable — the waiting decode future stays live
            self.decode.num_integrity_rejects += 1
            log.warning("rejecting corrupt KV frame for %s: %s", rid, exc)
            yield {"ok": False, "error": f"integrity: {exc}"}
            return
        try:
            await self.decode.engine.inject_kv(
                pending.seq, data,
                epoch=int(epoch) if epoch is not None else None,
            )
        except StaleEpochError as exc:
            self.decode.num_epoch_rejects += 1
            yield {"ok": False, "error": str(exc), "permanent": True}
            return
        except Exception as exc:
            if not pending.done.done():
                pending.done.set_exception(exc)
            yield {"ok": False, "error": str(exc), "permanent": True}
            return
        get_tracer().record(
            "disagg.inject", context, start_mono=t0,
            end_mono=time.monotonic(), attrs={"request_id": rid},
        )
        if not pending.done.done():
            pending.done.set_result(result)
        yield {"ok": True}


class DecodeHandler(AsyncEngine):
    """Decode worker: conditional remote prefill + resume
    (ref: handlers.py:89 DecodeWorkerHandler)."""

    def __init__(
        self,
        engine: InferenceEngine,
        prefill_client: Optional[Client] = None,
        config: Optional[DisaggConfig] = None,
        plane: Optional[DevicePlane] = None,
        store=None,
    ):
        self.engine = engine
        self.prefill_client = prefill_client
        self.config = config or DisaggConfig()
        self.store = store  # required for queue mode (use_queue)
        self.pending: Dict[str, PendingHandoff] = {}
        # request ids with a device-plane transfer in flight (the prefill
        # worker's liveness query marks these; our timeout path then grants
        # a grace period instead of freeing blocks mid-write)
        self.inflight: set = set()
        self._depth_task: Optional[asyncio.Task] = None
        self._sweep_task: Optional[asyncio.Task] = None
        self.kv_inject_addr: Optional[str] = None  # set after serving
        self.num_remote_prefills = 0
        self.num_local_prefills = 0
        self.num_fallbacks = 0
        self.num_epoch_rejects = 0
        self.num_integrity_rejects = 0
        self.num_orphans_reaped = 0
        # handoff-failure breaker: OPEN = unified-fallback cooldown, all
        # prefills run locally until the window passes (DynaServe-style)
        self.fallback_breaker = CircuitBreaker(self.config.breaker_config())
        # per-prefill-worker breakers (push mode): a flapping worker is
        # skipped by the round-robin pick while its breaker is open
        self.prefill_breakers = CircuitBreakerRegistry(
            self.config.breaker_config()
        )
        self._rr = 0
        # backlog signal for the planner, refreshed on every enqueue
        # (published via WorkerMetricsPublisher extra_fn)
        self.last_queue_depth = 0
        # advertise this engine on the device plane so a same-process
        # prefill worker transfers KV device-to-device instead of relaying
        self.plane = plane if plane is not None else default_plane
        self.plane_id: Optional[str] = None
        if hasattr(engine, "mesh"):  # device engines only (not mocker)
            self.plane_id = uuid.uuid4().hex
            self.plane.register(self.plane_id, engine)

    def close(self) -> None:
        """Drop the device-plane registration (the registry would otherwise
        pin the engine — and its KV cache — for the process lifetime)."""
        if self.plane_id is not None:
            self.plane.unregister(self.plane_id)
            self.plane_id = None
        if self._depth_task is not None:
            self._depth_task.cancel()
            self._depth_task = None
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            self._sweep_task = None

    def inject_handler(self) -> KvInjectHandler:
        return KvInjectHandler(self)

    def _should_remote_prefill(self, token_ids: list) -> bool:
        if self.kv_inject_addr is None:
            return False
        if not self.fallback_breaker.allow():
            # unified-fallback cooldown: recent handoffs kept failing, so
            # prefill locally until the breaker half-opens a probe slot
            return False
        if self.config.use_queue:
            if self.store is None:
                return False
            # with zero live prefill workers nobody will ever pop the
            # queue — go local immediately rather than stalling every
            # long prompt for queue_wait_s (the client is optional so
            # store-only test rigs still work)
            if (self.prefill_client is not None
                    and not self.prefill_client.instance_ids()):
                return False
        else:
            if (self.prefill_client is None
                    or not self.prefill_client.instance_ids()):
                return False
        if len(token_ids) < self.config.min_remote_prefill_tokens:
            return False
        if self.engine.stats.kv_usage > self.config.max_reserve_usage:
            return False
        return True

    def metrics_extra(self) -> dict:
        """Merged into the worker's load-metrics snapshot (planner input)."""
        return {
            "prefill_queue_depth": self.last_queue_depth,
            "disagg": {
                "fallback_total": float(self.num_fallbacks),
                "breaker_open": (
                    1.0 if self.fallback_breaker.state == OPEN else 0.0
                ),
                "orphans_reaped_total": float(self.num_orphans_reaped),
                "epoch_rejects_total": float(self.num_epoch_rejects),
            },
        }

    def start_depth_monitor(self, interval_s: float = 1.0) -> None:
        """Keep ``last_queue_depth`` fresh even when no pushes happen —
        a metric sampled only at enqueue time would report phantom backlog
        forever after a burst drains."""
        if self._depth_task is None and self.store is not None:
            self._depth_task = asyncio.create_task(
                self._depth_loop(interval_s)
            )

    async def _depth_loop(self, interval_s: float) -> None:
        while True:
            try:
                self.last_queue_depth = await self.store.q_len(
                    self.config.queue_name
                )
            except Exception:
                pass
            await asyncio.sleep(interval_s)

    # ----------------------- orphan GC ---------------------------------

    def start_orphan_sweeper(self) -> None:
        if self._sweep_task is None:
            from ..runtime.tasks import spawn_logged

            self._sweep_task = spawn_logged(
                self._sweep_loop(), name="disagg-decode-sweep"
            )

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.orphan_sweep_interval_s)
            self.sweep_orphans()

    def sweep_orphans(self) -> int:
        """Reap pending handoffs whose deadline long passed: wake the
        waiter (if any), and cancel the reservation iff its epoch is still
        live — a resumed or already-cancelled sequence is left alone."""
        now = time.monotonic()
        reaped = 0
        for rid, ph in list(self.pending.items()):
            grace = self.config.orphan_grace_s
            if rid in self.inflight:
                grace += self.config.inflight_grace_s
            if now <= ph.deadline + grace:
                continue
            if self.pending.pop(rid, None) is None:
                continue
            self.inflight.discard(rid)
            if not ph.done.done():
                ph.done.set_exception(
                    RuntimeError("handoff orphaned (deadline expired)")
                )
            ph.done.exception()  # mark retrieved if nobody is waiting
            if self.engine.reservation_valid(rid, ph.epoch):
                self.engine.cancel_reservation(ph.seq)
            self.num_orphans_reaped += 1
            reaped += 1
            log.warning("reaped orphaned handoff %s", rid)
        return reaped

    # ----------------------- generate ----------------------------------

    def _pick_prefill_worker(self) -> Optional[int]:
        """Round-robin over prefill instances whose breaker admits traffic
        (push mode). None = caller should use the client's own round_robin
        (test stubs without ``direct``/instance routing)."""
        client = self.prefill_client
        if client is None or not hasattr(client, "direct"):
            return None
        try:
            ids = list(client.instance_ids())
        except Exception:
            return None
        if not ids:
            return None
        allowed = [i for i in ids if self.prefill_breakers.allow(i)]
        # every breaker open: probe anyway rather than deadlocking disagg
        pool = allowed or ids
        self._rr += 1
        target = pool[self._rr % len(pool)]
        self.prefill_breakers.begin(target)
        return target

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        token_ids = list(request["token_ids"])
        if request.get("mm"):
            # multimodal prompts prefill locally: the remote prefill path
            # would need the embeddings shipped and spliced on the prefill
            # worker (future work); local keeps EPD correctness
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return
        if not self._should_remote_prefill(token_ids):
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return

        req = Request(
            request_id=context.id,
            token_ids=token_ids,
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
            eos_token_ids=tuple(request.get("eos_token_ids", ())),
            ignore_eos=bool(request.get("ignore_eos", False)),
        )
        seq = self.engine.reserve_sequence(req)
        if seq is None:  # pool can't host it — prefill locally instead
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return

        # the handoff budget: config cap, tightened by the request's own
        # remaining deadline (PR 1 propagation) when one is set
        budget = self.config.handoff_timeout_s
        rem = context.time_remaining()
        if rem is not None:
            budget = max(0.0, min(budget, rem))
        t0 = time.monotonic()
        done: asyncio.Future = asyncio.get_running_loop().create_future()
        self.pending[context.id] = PendingHandoff(
            seq=seq, done=done, epoch=seq.kv_epoch, deadline=t0 + budget,
        )
        self.fallback_breaker.begin()
        target: Optional[int] = None
        try:
            xfer = {
                "request_id": context.id,
                "addr": self.kv_inject_addr,
                "plane_id": self.plane_id,
                "block_ids": list(seq.block_table),
                "epoch": seq.kv_epoch,
                "deadline": time.time() + budget,
            }
            if context.trace is not None:
                xfer["traceparent"] = context.trace.traceparent()
            prefill_request = {
                "token_ids": token_ids,
                "temperature": req.temperature,
                "top_k": req.top_k,
                "top_p": req.top_p,
                "seed": req.seed,
                "kv_transfer": xfer,
            }
            first_token: Optional[int] = None
            if self.config.use_queue:
                # queue mode: enqueue and wait — the inject payload carries
                # the first token (or the failure) back to us
                import msgpack

                wait_s = min(self.config.queue_wait_s, budget)
                prefill_request["queue_deadline"] = time.time() + wait_s
                await self.store.q_push(
                    self.config.queue_name, msgpack.packb(prefill_request)
                )
                try:
                    self.last_queue_depth = await self.store.q_len(
                        self.config.queue_name
                    )
                except Exception:
                    pass
                try:
                    result = await asyncio.wait_for(done, timeout=wait_s)
                except asyncio.TimeoutError:
                    if context.id not in self.inflight:
                        raise
                    # a device-plane transfer is mid-write into our
                    # reserved blocks — freeing them now would hand
                    # corrupted blocks to the next request; grant a grace
                    # window for the transfer to land
                    result = await asyncio.wait_for(
                        done, timeout=self.config.inflight_grace_s
                    )
                # bool is an int subclass — require a real token id, not
                # the legacy True completion marker
                if type(result) is not int:
                    raise RuntimeError(
                        "queued prefill completed without a first token"
                    )
                first_token = result
            else:
                target = self._pick_prefill_worker()
                if target is not None:
                    stream = self.prefill_client.direct(
                        target, prefill_request, context
                    )
                else:
                    stream = self.prefill_client.round_robin(
                        prefill_request, context
                    )
                async for item in stream:
                    first_token = item["token_ids"][0]
                if first_token is None:
                    raise RuntimeError("prefill worker returned no token")
                wait_s = max(0.05, budget - (time.monotonic() - t0))
                try:
                    await asyncio.wait_for(done, timeout=wait_s)
                except asyncio.TimeoutError:
                    if context.id not in self.inflight:
                        raise
                    await asyncio.wait_for(
                        done, timeout=self.config.inflight_grace_s
                    )
            self.num_remote_prefills += 1
            self.fallback_breaker.record_success()
            if target is not None:
                self.prefill_breakers.record_success(target)
            get_tracer().record(
                "disagg.handoff", context, start_mono=t0,
                end_mono=time.monotonic(),
                attrs={"request_id": context.id,
                       "prompt_tokens": len(token_ids),
                       "epoch": seq.kv_epoch},
            )
            log.debug("remote prefill complete: %s (%d tokens)",
                      context.id, len(token_ids))
        except asyncio.CancelledError:
            # client went away mid-handoff: free the reservation (the
            # epoch guard rejects any transfer that lands later)
            self.engine.cancel_reservation(seq)
            raise
        except Exception:
            # remote prefill failed — fall back to local so the request
            # still completes (the Migration operator retries above us for
            # stream-level failures); the failure feeds the breakers
            log.exception("remote prefill failed — falling back to local")
            self.fallback_breaker.record_failure()
            if target is not None:
                self.prefill_breakers.record_failure(target)
            self.num_fallbacks += 1
            get_tracer().record(
                "disagg.handoff", context, start_mono=t0,
                end_mono=time.monotonic(), status="error",
                status_detail="fallback_local",
                attrs={"request_id": context.id},
            )
            self.engine.cancel_reservation(seq)
            self.pending.pop(context.id, None)
            self.inflight.discard(context.id)
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return
        finally:
            self.pending.pop(context.id, None)
            self.inflight.discard(context.id)

        async def _on_stop() -> None:
            await context.wait_stopped()
            self.engine.abort(req.request_id,
                              "killed" if context.is_killed() else "cancelled")

        watcher = asyncio.create_task(_on_stop())
        try:
            async for out in self.engine.resume_prefilled(seq, first_token):
                if context.is_killed():
                    return
                yield {
                    "token_ids": [out.token_id],
                    "index": out.index,
                    "finished": out.finished,
                    "finish_reason": out.finish_reason,
                    "num_prompt_tokens": out.num_prompt_tokens,
                }
                if out.finished:
                    return
            # engine path exhausted without a finished marker (abort):
            # nothing further to yield
        finally:
            watcher.cancel()
