"""Decode/prefill worker handlers for disaggregated serving
(ref: components/backends/vllm/src/dynamo/vllm/handlers.py:89 Decode, :207
Prefill; conditional thresholds ref: lib/llm/src/disagg_router.rs:230).

Flow (decode-orchestrated, matching the reference):

  DecodeHandler.generate(request)
    ├─ below threshold / no prefill workers / pool full → local engine path
    ├─ reserve blocks on the decode engine
    ├─ push prefill request to a prefill worker (round-robin), carrying
    │  kv_transfer params {addr, request_id} — our kv_inject ingress addr
    ├─ PrefillHandler: engine.prefill_held → extract_kv → push blocks to
    │  decode's kv_inject endpoint → respond {token_id}
    ├─ inject arrives concurrently; decode awaits its completion event
    └─ engine.resume_prefilled(seq, first_token) → decode stream

The prefill worker *pushes* KV into pre-allocated decode blocks (the NIXL
write direction); bulk bytes ride the TCP transport's binary frames while
control messages carry only block metadata.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, Optional

import uuid

from ..engine.engine import EngineCore, InferenceEngine, Request
from ..runtime.component import Client
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..utils.logging import get_logger
from .ici import DevicePlane, default_plane
from .protocol import kv_from_wire, kv_to_wire

log = get_logger("disagg")


@dataclass
class DisaggConfig:
    """Conditional-disagg thresholds (ref: disagg_router.rs:230 — remote
    prefill only when the *new* work is long enough to be worth the
    transfer)."""

    min_remote_prefill_tokens: int = 32
    # refuse remote prefill when the decode pool is above this usage
    max_reserve_usage: float = 0.95


class PrefillHandler(AsyncEngine):
    """Prefill worker: bounded prefill + KV push-back
    (ref: handlers.py:207 PrefillWorkerHandler)."""

    def __init__(self, engine: InferenceEngine,
                 plane: Optional[DevicePlane] = None):
        self.engine = engine
        self.plane = plane if plane is not None else default_plane
        self.num_device_transfers = 0
        self.num_relay_transfers = 0

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        xfer: Dict[str, Any] = request.get("kv_transfer") or {}
        req = Request(
            request_id=context.id,
            token_ids=list(request["token_ids"]),
            max_tokens=1,
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
        )
        seq, first_token = await self.engine.prefill_held(req)
        dst_engine = self.plane.get(xfer.get("plane_id"))
        dst_ids = list(xfer.get("block_ids") or [])
        if dst_engine is not None and dst_ids:
            # device plane: blocks move src→dst on device (ICI), control
            # message carries only the completion flag — the reference's
            # "messages carry only block IDs" design taken to its limit
            try:
                if len(seq.block_table) < len(dst_ids):
                    raise RuntimeError(
                        f"held {len(seq.block_table)} blocks < "
                        f"{len(dst_ids)} reserved"
                    )
                await self.plane.transfer(
                    self.engine, list(seq.block_table)[: len(dst_ids)],
                    dst_engine, dst_ids,
                )
            finally:
                self.engine.release_held(seq)
            self.num_device_transfers += 1
            payload: Dict[str, Any] = {"device_done": True}
        else:
            try:
                data = await self.engine.extract_kv(seq)
            finally:
                self.engine.release_held(seq)
            self.num_relay_transfers += 1
            payload = kv_to_wire(data)
        payload["request_id"] = xfer["request_id"]
        # push the blocks into the decode worker's pre-allocated slots
        transport = self.engine_runtime_transport(context)
        async for ack in transport.generate(xfer["addr"], payload, Context()):
            if not ack.get("ok", False):
                raise RuntimeError(f"kv inject rejected: {ack}")
        yield {"token_ids": [first_token], "finished": True,
               "finish_reason": "remote_prefill"}

    # seam for tests / runtime injection
    def engine_runtime_transport(self, context: Context):
        from ..runtime.transport import TransportClient

        if not hasattr(self, "_transport"):
            self._transport = TransportClient()
        return self._transport


class KvInjectHandler(AsyncEngine):
    """Decode-worker ingress for pushed KV blocks: scatters the payload
    into the reserved sequence's blocks and signals the waiting decode
    handler."""

    def __init__(self, decode: "DecodeHandler"):
        self.decode = decode

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        rid = request["request_id"]
        pending = self.decode.pending.get(rid)
        if pending is None:
            yield {"ok": False, "error": f"unknown request {rid}"}
            return
        seq, done = pending
        if request.get("device_done"):
            # blocks already arrived over the device plane — this is just
            # the completion signal
            if not done.done():
                done.set_result(True)
            yield {"ok": True}
            return
        try:
            await self.decode.engine.inject_kv(seq, kv_from_wire(request))
        except Exception as exc:
            if not done.done():
                done.set_exception(exc)
            yield {"ok": False, "error": str(exc)}
            return
        if not done.done():
            done.set_result(True)
        yield {"ok": True}


class DecodeHandler(AsyncEngine):
    """Decode worker: conditional remote prefill + resume
    (ref: handlers.py:89 DecodeWorkerHandler)."""

    def __init__(
        self,
        engine: InferenceEngine,
        prefill_client: Optional[Client] = None,
        config: Optional[DisaggConfig] = None,
        plane: Optional[DevicePlane] = None,
    ):
        self.engine = engine
        self.prefill_client = prefill_client
        self.config = config or DisaggConfig()
        # request_id -> (reserved seq, inject-complete future)
        self.pending: Dict[str, tuple] = {}
        self.kv_inject_addr: Optional[str] = None  # set after serving
        self.num_remote_prefills = 0
        self.num_local_prefills = 0
        # advertise this engine on the device plane so a same-process
        # prefill worker transfers KV device-to-device instead of relaying
        self.plane = plane if plane is not None else default_plane
        self.plane_id: Optional[str] = None
        if hasattr(engine, "mesh"):  # device engines only (not mocker)
            self.plane_id = uuid.uuid4().hex
            self.plane.register(self.plane_id, engine)

    def close(self) -> None:
        """Drop the device-plane registration (the registry would otherwise
        pin the engine — and its KV cache — for the process lifetime)."""
        if self.plane_id is not None:
            self.plane.unregister(self.plane_id)
            self.plane_id = None

    def inject_handler(self) -> KvInjectHandler:
        return KvInjectHandler(self)

    def _should_remote_prefill(self, token_ids: list) -> bool:
        if self.prefill_client is None or self.kv_inject_addr is None:
            return False
        if not self.prefill_client.instance_ids():
            return False
        if len(token_ids) < self.config.min_remote_prefill_tokens:
            return False
        if self.engine.stats.kv_usage > self.config.max_reserve_usage:
            return False
        return True

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        token_ids = list(request["token_ids"])
        if not self._should_remote_prefill(token_ids):
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return

        req = Request(
            request_id=context.id,
            token_ids=token_ids,
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
            eos_token_ids=tuple(request.get("eos_token_ids", ())),
            ignore_eos=bool(request.get("ignore_eos", False)),
        )
        seq = self.engine.reserve_sequence(req)
        if seq is None:  # pool can't host it — prefill locally instead
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return

        done: asyncio.Future = asyncio.get_running_loop().create_future()
        self.pending[context.id] = (seq, done)
        try:
            prefill_request = {
                "token_ids": token_ids,
                "temperature": req.temperature,
                "top_k": req.top_k,
                "top_p": req.top_p,
                "seed": req.seed,
                "kv_transfer": {
                    "request_id": context.id,
                    "addr": self.kv_inject_addr,
                    "plane_id": self.plane_id,
                    "block_ids": list(seq.block_table),
                },
            }
            first_token: Optional[int] = None
            async for item in self.prefill_client.round_robin(
                prefill_request, context
            ):
                first_token = item["token_ids"][0]
            if first_token is None:
                raise RuntimeError("prefill worker returned no token")
            await asyncio.wait_for(done, timeout=120.0)
            self.num_remote_prefills += 1
            log.debug("remote prefill complete: %s (%d tokens)",
                      context.id, len(token_ids))
        except Exception:
            # remote prefill failed — fall back to local so the request
            # still completes (the Migration operator retries above us for
            # stream-level failures)
            log.exception("remote prefill failed — falling back to local")
            self.engine.cancel_reservation(seq)
            self.pending.pop(context.id, None)
            self.num_local_prefills += 1
            async for out in self.engine.generate(request, context):
                yield out
            return
        finally:
            self.pending.pop(context.id, None)

        async def _on_stop() -> None:
            await context.wait_stopped()
            self.engine.abort(req.request_id,
                              "killed" if context.is_killed() else "cancelled")

        watcher = asyncio.create_task(_on_stop())
        try:
            async for out in self.engine.resume_prefilled(seq, first_token):
                if context.is_killed():
                    return
                yield {
                    "token_ids": [out.token_id],
                    "index": out.index,
                    "finished": out.finished,
                    "finish_reason": out.finish_reason,
                    "num_prompt_tokens": out.num_prompt_tokens,
                }
                if out.finished:
                    return
            # engine path exhausted without a finished marker (abort):
            # nothing further to yield
        finally:
            watcher.cancel()
