"""Engine worker process: build the JAX engine, serve it, register the model.

Role-equivalent to the reference's backend worker mains (ref: components/
backends/vllm/src/dynamo/vllm/main.py:184,325): create the runtime, start the
inference engine, expose ``generate`` (+ ``clear_kv_blocks``) endpoints, and
register the model so frontends discover it.

    python -m dynamo_tpu.worker --model tiny --model-name demo \
        --tokenizer /path/tokenizer.json
"""

from __future__ import annotations

import argparse
import asyncio
import os
from typing import Optional

from .engine.config import EngineConfig, ModelConfig
from .engine.engine import InferenceEngine
from .llm.tokenizer import Tokenizer
from .runtime.component import DistributedRuntime
from .serving import ServeOptions, load_tokenizer, run_until_shutdown, serve_engine
from .utils.config import RuntimeConfig
from .utils.logging import get_logger

log = get_logger("worker")

MODEL_PRESETS = {
    "tiny": ModelConfig.tiny,
    "1b": ModelConfig.llama3_1b,
    "8b": ModelConfig.llama3_8b,
    "70b": ModelConfig.llama3_70b,
}


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu engine worker")
    p.add_argument("--model", default="tiny", choices=sorted(MODEL_PRESETS))
    p.add_argument("--model-name", default=None,
                   help="served model name (default: preset name)")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer.json path or HF model dir")
    p.add_argument("--tool-call-parser", default=None,
                   choices=["hermes", "json", "pythonic"],
                   help="streaming tool-call parser advertised in the MDC")
    p.add_argument("--reasoning-parser", default=None,
                   help="set to split <think>…</think> into "
                        "reasoning_content (e.g. 'think')")
    p.add_argument("--weights", default=None,
                   help="HF checkpoint dir (*.safetensors [+ config.json, "
                        "which overrides --model]; tokenizer defaults to "
                        "the same dir)")
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=2048)
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-batched-tokens", type=int, default=512)
    p.add_argument("--max-model-len", type=int, default=8192)
    p.add_argument("--mesh", default="1,1", help="dp,tp mesh axis sizes")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (layers stage-sharded, "
                        "GPipe-microbatched decode; exclusive with --mesh)")
    p.add_argument("--pp-microbatches", type=int, default=4)
    p.add_argument("--migration-limit", type=int, default=3)
    p.add_argument("--spec-mode", default=None, choices=["off", "ngram"],
                   help="speculative decoding: device-side n-gram drafting "
                        "+ batched verify (default: DYNTPU_SPEC_MODE, off)")
    p.add_argument("--spec-k", type=int, default=None,
                   help="max draft tokens verified per window "
                        "(default: DYNTPU_SPEC_K, 4)")
    p.add_argument("--attention-impl", default="pallas",
                   choices=["pallas", "einsum", "auto"],
                   help="decode attention path; 'auto' probes both on the "
                        "live backend at startup and picks per-shape-class "
                        "winners (decode / spec window / prefill chunk)")
    p.add_argument("--weight-dtype", default=None,
                   choices=["bf16", "int8", "fp8"],
                   help="weight storage dtype: int8/fp8 quantize at load "
                        "time with per-channel scales "
                        "(default: DYNTPU_WEIGHT_DTYPE, bf16)")
    p.add_argument("--kv-dtype", default=None,
                   choices=["bf16", "int8", "fp8"],
                   help="paged-KV storage dtype: int8/fp8 halve KV bytes "
                        "per token with per-token scales "
                        "(default: DYNTPU_KV_DTYPE, bf16)")
    p.add_argument("--prefill-chunk-tokens", type=int, default=None,
                   help="cap each prefill chunk at this many tokens so long "
                        "prompts interleave with running decodes instead of "
                        "stalling them (0 = whole-bucket prefill; default: "
                        "DYNTPU_PREFILL_CHUNK_TOKENS, 0)")
    p.add_argument("--drain-timeout", type=float, default=None,
                   help="seconds in-flight streams get to finish on graceful "
                        "drain before being stopped for client migration "
                        "(default: DYNTPU_DRAIN_TIMEOUT_S, 30)")
    p.add_argument("--advertise-host", default="127.0.0.1")
    p.add_argument(
        "--disagg-mode", default="agg", choices=["agg", "decode", "prefill"],
        help="aggregated, decode-orchestrator, or prefill worker "
             "(ref: disagg_serving.md)",
    )
    p.add_argument("--prefill-component", default="prefill")
    p.add_argument("--min-remote-prefill-tokens", type=int, default=32)
    p.add_argument(
        "--disagg-queue", action="store_true",
        help="queue-based disagg: decode q_pushes prefill work onto the "
             "store work queue, prefill workers q_pop (ref: the JetStream "
             "prefill queue); default is direct round-robin push",
    )
    p.add_argument("--disagg-queue-name", default="prefill_queue")
    p.add_argument("--kvbm-host-blocks", type=int, default=0,
                   help="G2 host-tier capacity in blocks (0 = KVBM off)")
    p.add_argument("--kvbm-host-bytes", type=int, default=0,
                   help="G2 host-tier capacity in bytes (0 = unbounded); "
                        "byte-bounding lets a quantized (int8/fp8) KV "
                        "cache hold ~2x the blocks in the same budget")
    p.add_argument("--kvbm-disk-dir", default=None)
    p.add_argument("--kvbm-disk-blocks", type=int, default=0)
    p.add_argument("--kvbm-remote", action="store_true",
                   help="enable the G4 cluster-shared tier in the store")
    p.add_argument("--kvbm-distributed", action="store_true",
                   help="share the G2 host tier across workers (presence "
                        "keys in the store + direct TCP block fetch; ref: "
                        "block_manager/distributed)")
    p.add_argument("--kvbm-group", default=None,
                   help="distributed-KVBM group name for barrier bring-up")
    p.add_argument("--kvbm-group-role", choices=["leader", "worker"],
                   default="worker")
    p.add_argument("--kvbm-group-size", type=int, default=1,
                   help="worker count the group leader waits for")
    p.add_argument("--mm-encoder", action="store_true",
                   help="serve a colocated vision encode endpoint and "
                        "advertise multimodal support (EPD; a standalone "
                        "encode worker is python -m dynamo_tpu.multimodal)")
    p.add_argument("--mm-image-size", type=int, default=32)
    p.add_argument("--mm-patch-size", type=int, default=8)
    p.add_argument("--mm-encode-component", default=None,
                   help="advertise a REMOTE encode worker's component "
                        "instead of serving one here")
    # multi-host SPMD (one process per host of a slice; flags default to
    # the JAX_* env vars so TPU pod launchers can set them uniformly)
    p.add_argument("--coordinator",
                   default=os.environ.get("JAX_COORDINATOR_ADDRESS"),
                   help="host0 ip:port for jax.distributed (multi-host)")
    p.add_argument("--num-hosts", type=int,
                   default=int(os.environ.get("JAX_PROCESS_COUNT", "1")))
    p.add_argument("--host-index", type=int,
                   default=int(os.environ.get("JAX_PROCESS_INDEX", "0")))
    return p.parse_args(argv)


async def run_worker(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace
    if args.drain_timeout is not None:
        config.drain_timeout_s = args.drain_timeout

    from .parallel.multihost import MultihostConfig, initialize_distributed

    mh = MultihostConfig(
        coordinator=args.coordinator, num_hosts=args.num_hosts,
        host_index=args.host_index,
    )
    # must precede every other JAX call — it decides the backend topology
    initialize_distributed(mh)
    if mh.enabled and (args.disagg_mode != "agg"
                       or args.kvbm_host_blocks > 0):
        raise SystemExit(
            "multi-host workers serve the aggregated path only "
            "(disagg/KVBM are single-host features)"
        )

    dp, tp = (int(x) for x in args.mesh.split(","))
    model_cfg = MODEL_PRESETS[args.model]()
    weight_dtype = (args.weight_dtype if args.weight_dtype is not None
                    else config.weight_dtype)
    kv_dtype = (args.kv_dtype if args.kv_dtype is not None
                else config.kv_dtype)
    params = None
    if args.weights:
        from .engine.weights import (
            load_hf_params, load_hf_params_sharded, model_config_from_hf,
        )

        if os.path.exists(os.path.join(args.weights, "config.json")):
            model_cfg = model_config_from_hf(args.weights)
        if dp * tp > 1 and args.pp <= 1:
            # stream onto device shards (peak host memory = one tensor)
            import jax

            from .engine import model as model_lib

            mesh = model_lib.make_mesh((dp, tp), jax.devices())
            params = load_hf_params_sharded(
                args.weights, model_cfg, mesh, weight_dtype)
        else:
            params = load_hf_params(args.weights, model_cfg, weight_dtype)
        if args.tokenizer is None:
            args.tokenizer = args.weights
    eng_cfg = EngineConfig(
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_num_seqs=args.max_num_seqs,
        max_num_batched_tokens=args.max_batched_tokens,
        max_model_len=min(args.max_model_len, model_cfg.max_position),
        mesh_shape=(dp, tp),
        pp_stages=args.pp,
        pp_microbatches=args.pp_microbatches,
        attention_impl=args.attention_impl,
        prefill_chunk_tokens=(
            args.prefill_chunk_tokens
            if args.prefill_chunk_tokens is not None
            else config.prefill_chunk_tokens
        ),
        spec_mode=(args.spec_mode if args.spec_mode is not None
                   else config.spec_mode),
        spec_k=(args.spec_k if args.spec_k is not None else config.spec_k),
        spec_auto_disable_threshold=config.spec_auto_disable_threshold,
        spec_auto_disable_window=config.spec_auto_disable_window,
        weight_dtype=weight_dtype,
        kv_dtype=kv_dtype,
    )
    tokenizer = load_tokenizer(args.tokenizer)
    name = args.model_name or args.model

    # Build the engine BEFORE taking the store lease: engine construction is
    # seconds of synchronous JAX work (param init, device_put) that would
    # starve the lease keepalive and get the worker evicted at birth.
    engine = InferenceEngine(model_cfg, eng_cfg, params=params)
    runtime = await DistributedRuntime.from_settings(config)

    if mh.enabled and not mh.is_leader:
        # follower: replay the leader's step plans; no serving, no
        # registration — the leader is the slice's single front door
        from .parallel.multihost import follower_loop

        log.info("worker ready: model=%s mode=follower host=%d/%d",
                 name, mh.host_index, mh.num_hosts)
        try:
            await follower_loop(runtime, engine, mh, name,
                                component=args.component)
            log.warning("follower exiting (leader lost)")
        except BaseException:  # dynalint: disable=DT303 — os._exit below
            # the traceback must hit the log BEFORE the hard exit below
            # discards it — a replay bug would otherwise masquerade as
            # endless "leader lost" restarts
            log.exception("follower loop terminated abnormally")
        finally:
            try:
                await asyncio.wait_for(engine.stop(), timeout=10)
                await asyncio.wait_for(runtime.shutdown(), timeout=10)
            except BaseException:  # dynalint: disable=DT303
                # incl. CancelledError — the hard os._exit(1) below is the
                # contract; nothing may skip it
                log.exception("follower cleanup failed")
            # hard exit: jax.distributed's atexit barrier blocks forever
            # when the coordinator host is gone, and the supervisor's
            # restart contract needs a DEAD process, not a graceful-looking
            # hang
            os._exit(1)
        return

    if mh.enabled:
        # leader: stream every executed step to the followers, and gate
        # model registration on all of them being connected
        from .parallel.multihost import (
            StepBroadcaster, StepStreamHandler, leader_gate,
        )

        broadcaster = StepBroadcaster(asyncio.get_running_loop())
        engine.step_sink = broadcaster.sink
        step_ep = (runtime.namespace().component(args.component)
                   .endpoint("step_stream"))
        await step_ep.serve_endpoint(
            StepStreamHandler(broadcaster,
                              heartbeat_interval_s=mh.heartbeat_interval_s),
            advertise_host=args.advertise_host,
        )
        await leader_gate(runtime.store, mh, broadcaster, name)

    if args.kvbm_host_blocks > 0:
        from .kvbm.manager import KvbmConfig, StoreRemoteTier

        remote = None
        if args.kvbm_remote:
            remote = StoreRemoteTier(
                runtime.store, namespace=config.namespace
            )
        engine.attach_kvbm(KvbmConfig(
            host_blocks=args.kvbm_host_blocks,
            host_bytes=args.kvbm_host_bytes,
            disk_dir=args.kvbm_disk_dir,
            disk_blocks=args.kvbm_disk_blocks,
        ), remote=remote)

    kvbm_dist = None
    if args.kvbm_group:
        # a group member that never starts the presence plane would leave
        # the leader waiting at the barrier for a check-in that never comes
        args.kvbm_distributed = True
    if args.kvbm_distributed and engine.kvbm is None:
        raise SystemExit(
            "--kvbm-distributed/--kvbm-group require KVBM "
            "(--kvbm-host-blocks > 0)"
        )
    if args.kvbm_distributed:
        from .kvbm.distributed import (
            DistributedKvbm, KvbmGroup, engine_layout,
        )

        kvbm_dist = DistributedKvbm(
            engine.kvbm, runtime.store, runtime.primary_lease,
            namespace=config.namespace, advertise_host=args.advertise_host,
            scope=name,
        )
        await kvbm_dist.start()
        if args.kvbm_group:
            layout = engine_layout(engine)
            if args.kvbm_group_role == "leader":
                await KvbmGroup.lead(
                    runtime.store, args.kvbm_group, args.kvbm_group_size,
                    layout,
                )
            else:
                await KvbmGroup.join(
                    runtime.store, args.kvbm_group,
                    f"worker-{runtime.primary_lease}", layout,
                )
            log.info("kvbm group %s formed (%s)", args.kvbm_group,
                     args.kvbm_group_role)

    if config.prefix_enabled:
        from .prefix.manager import PrefixCacheConfig

        # after attach_kvbm so the manager chains the host-pool drop hook
        # and mirrors the G2/G4 tiers; works index-only without KVBM
        engine.attach_prefix_cache(
            config=PrefixCacheConfig(
                evict_to_host_blocks=config.prefix_evict_blocks,
                tier_weight_g2=config.prefix_tier_weight_g2,
                tier_weight_g4=config.prefix_tier_weight_g4,
            ),
            worker_id=runtime.primary_lease,
        )

    handler = None
    queue_worker = None
    component = args.component
    if args.disagg_mode == "prefill":
        from .disagg import DisaggConfig, PrefillHandler, PrefillQueueWorker

        # prefill workers serve on their own component; decode workers own
        # model registration (ref: vllm main.py:137 init_prefill)
        component = args.prefill_component
        handler = PrefillHandler(
            engine, config=DisaggConfig.from_runtime(config)
        )
        handler.start_orphan_sweeper()
        if args.disagg_queue:
            queue_worker = PrefillQueueWorker(
                handler, runtime.store, queue_name=args.disagg_queue_name
            )
            queue_worker.start()
        tokenizer = None
    elif args.disagg_mode == "decode":
        from .disagg import DecodeHandler, DisaggConfig

        prefill_client = await (
            runtime.namespace().component(args.prefill_component)
            .endpoint("generate").client()
        )
        handler = DecodeHandler(
            engine, prefill_client,
            DisaggConfig.from_runtime(
                config,
                min_remote_prefill_tokens=args.min_remote_prefill_tokens,
                use_queue=args.disagg_queue,
                queue_name=args.disagg_queue_name,
            ),
            store=runtime.store,
        )
        handler.start_orphan_sweeper()

    mm_opts = None
    mm_handler = None
    if args.mm_encoder or args.mm_encode_component:
        from .multimodal import (
            EncodeHandler, VisionEncoder, VisionEncoderConfig,
        )

        vcfg = VisionEncoderConfig(
            image_size=args.mm_image_size, patch_size=args.mm_patch_size,
            model_dim=model_cfg.hidden_size,
        )
        mm_opts = {
            "tokens_per_image": vcfg.tokens_per_image,
            "image_size": vcfg.image_size,
            "component": args.mm_encode_component or component,
            "endpoint": "encode",
        }
        if not args.mm_encode_component:
            mm_handler = EncodeHandler(VisionEncoder(vcfg))

    opts = ServeOptions(
        name=name, component=component, endpoint=args.endpoint,
        advertise_host=args.advertise_host,
        migration_limit=args.migration_limit,
        tool_call_parser=args.tool_call_parser,
        reasoning_parser=args.reasoning_parser,
        mm=mm_opts, mm_handler=mm_handler,
    )
    served, kv_pub, metrics_pub = await serve_engine(
        runtime, engine, eng_cfg, opts, tokenizer, handler=handler
    )
    if args.disagg_mode in ("prefill", "decode"):
        # surface the disagg health gauges (fallbacks, breaker state,
        # retries, orphan reaps — and in queue mode the prefill backlog)
        # to the planner via load metrics
        metrics_pub.extra_fn = handler.metrics_extra
    if args.disagg_mode == "decode" and args.disagg_queue:
        handler.start_depth_monitor()
    if args.disagg_mode == "decode":
        inject_ep = (runtime.namespace().component(component)
                     .endpoint("kv_inject"))
        inject_served = await inject_ep.serve_endpoint(
            handler.inject_handler(), advertise_host=args.advertise_host
        )
        handler.kv_inject_addr = inject_served.instance.addr

    log.info("worker ready: model=%s mode=%s engine=%s",
             name, args.disagg_mode, eng_cfg)
    try:
        await run_until_shutdown(runtime, engine, served, kv_pub,
                                 metrics_pub)
    finally:
        if queue_worker is not None:
            await queue_worker.stop()
        if kvbm_dist is not None:
            await kvbm_dist.stop()
        if hasattr(handler, "close"):
            handler.close()


def main(argv=None) -> None:
    asyncio.run(run_worker(parse_args(argv)))


if __name__ == "__main__":
    main()
