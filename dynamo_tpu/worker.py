"""Engine worker process: build the JAX engine, serve it, register the model.

Role-equivalent to the reference's backend worker mains (ref: components/
backends/vllm/src/dynamo/vllm/main.py:184,325): create the runtime, start the
inference engine, expose ``generate`` (+ ``clear_kv_blocks``) endpoints, and
register the model so frontends discover it.

    python -m dynamo_tpu.worker --model tiny --model-name demo \
        --tokenizer /path/tokenizer.json
"""

from __future__ import annotations

import argparse
import asyncio
import signal
from typing import Optional

from .engine.config import EngineConfig, ModelConfig
from .engine.engine import InferenceEngine
from .llm.discovery import ModelDeploymentCard, register_llm
from .llm.tokenizer import Tokenizer
from .runtime.component import DistributedRuntime
from .utils.config import RuntimeConfig
from .utils.logging import get_logger

log = get_logger("worker")

MODEL_PRESETS = {
    "tiny": ModelConfig.tiny,
    "1b": ModelConfig.llama3_1b,
    "8b": ModelConfig.llama3_8b,
    "70b": ModelConfig.llama3_70b,
}


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu engine worker")
    p.add_argument("--model", default="tiny", choices=sorted(MODEL_PRESETS))
    p.add_argument("--model-name", default=None,
                   help="served model name (default: preset name)")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer.json path or HF model dir")
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=2048)
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-batched-tokens", type=int, default=512)
    p.add_argument("--max-model-len", type=int, default=8192)
    p.add_argument("--mesh", default="1,1", help="dp,tp mesh axis sizes")
    p.add_argument("--migration-limit", type=int, default=3)
    p.add_argument("--advertise-host", default="127.0.0.1")
    return p.parse_args(argv)


def load_tokenizer(path: Optional[str]) -> Optional[Tokenizer]:
    if path is None:
        return None
    import os

    if os.path.isdir(path):
        return Tokenizer.from_pretrained_dir(path)
    return Tokenizer.from_file(path)


async def run_worker(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace

    dp, tp = (int(x) for x in args.mesh.split(","))
    model_cfg = MODEL_PRESETS[args.model]()
    eng_cfg = EngineConfig(
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_num_seqs=args.max_num_seqs,
        max_num_batched_tokens=args.max_batched_tokens,
        max_model_len=min(args.max_model_len, model_cfg.max_position),
        mesh_shape=(dp, tp),
    )
    tokenizer = load_tokenizer(args.tokenizer)
    name = args.model_name or args.model

    # Build the engine BEFORE taking the store lease: engine construction is
    # seconds of synchronous JAX work (param init, device_put) that would
    # starve the lease keepalive and get the worker evicted at birth.
    engine = InferenceEngine(model_cfg, eng_cfg)
    runtime = await DistributedRuntime.from_settings(config)
    await engine.start()

    endpoint = (runtime.namespace().component(args.component)
                .endpoint(args.endpoint))
    served = await endpoint.serve_endpoint(
        engine, advertise_host=args.advertise_host,
        metadata={"model": name},
    )

    # KV events + load metrics for the KV-aware router / aggregator
    # (ref: publisher.rs; the in-process seam replaces the ZMQ relay)
    from .router.publisher import KvEventPublisher, WorkerMetricsPublisher

    kv_pub = KvEventPublisher(endpoint.component, runtime.primary_lease)
    kv_pub.start()
    engine.kv_event_sink = kv_pub.sink
    metrics_pub = WorkerMetricsPublisher(
        endpoint.component, runtime.primary_lease, lambda: engine.stats
    )
    metrics_pub.start()

    async def clear_kv(request, context):
        engine.clear_kv_blocks()
        yield {"cleared": True}

    clear_ep = (runtime.namespace().component(args.component)
                .endpoint("clear_kv_blocks"))
    await clear_ep.serve_endpoint(
        clear_kv, advertise_host=args.advertise_host
    )

    if tokenizer is not None:
        card = ModelDeploymentCard(
            name=name,
            tokenizer_json=tokenizer.to_json_str(),
            chat_template=tokenizer.chat_template,
            context_length=eng_cfg.max_model_len,
            kv_block_size=eng_cfg.block_size,
            migration_limit=args.migration_limit,
            eos_token_ids=list(tokenizer.eos_token_ids),
            bos_token_id=tokenizer.bos_token_id,
            runtime_config={
                "total_kv_blocks": eng_cfg.num_blocks,
                "max_num_seqs": eng_cfg.max_num_seqs,
                "max_num_batched_tokens": eng_cfg.max_num_batched_tokens,
            },
        )
        await register_llm(endpoint, card)

    loop = asyncio.get_running_loop()

    def _graceful():
        log.info("signal received — draining")
        asyncio.ensure_future(_shutdown())

    async def _shutdown():
        await served.drain_and_stop()
        await kv_pub.stop()
        await metrics_pub.stop()
        await engine.stop()
        await runtime.shutdown()

    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, _graceful)

    log.info("worker ready: model=%s engine=%s", name, eng_cfg)
    await runtime.shutdown_event.wait()


def main(argv=None) -> None:
    asyncio.run(run_worker(parse_args(argv)))


if __name__ == "__main__":
    main()
