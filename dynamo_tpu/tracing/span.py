"""The span model: one timed, attributed window of work inside a trace.

A span carries the W3C ids the transport already propagates
(``utils/logging.TraceContext``), monotonic start/end stamps for precise
in-process durations, and a wall-clock anchor (``start_unix``) so spans
recorded on different hosts can be laid on one timeline by the offline
assembler. Point events are (offset-from-start, name, attrs) tuples —
cheap to record, trivially ordered.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

STATUS_OK = "ok"
STATUS_ERROR = "error"


@dataclass
class Span:
    """One unit of timed work. ``span_id`` is its identity inside the trace;
    ``parent_span_id`` links it into the tree the assembler rebuilds."""

    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    start_mono: float = 0.0
    end_mono: Optional[float] = None
    start_unix: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)
    events: List[Tuple[float, str, Optional[dict]]] = field(
        default_factory=list
    )
    status: str = STATUS_OK
    status_detail: Optional[str] = None
    # process-local root (frontend request / worker ingress): the slow-dump
    # decision keys off roots, since only they see the full duration
    root: bool = False
    # back-reference so span.end() reports to the collector that minted it;
    # excluded from equality/repr — it is plumbing, not data
    _collector: Any = field(default=None, repr=False, compare=False)

    @property
    def duration_s(self) -> Optional[float]:
        if self.end_mono is None:
            return None
        return self.end_mono - self.start_mono

    @property
    def ended(self) -> bool:
        return self.end_mono is not None

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, attrs: Optional[dict] = None) -> None:
        self.events.append((time.monotonic() - self.start_mono, name, attrs))

    def set_status(self, status: str, detail: Optional[str] = None) -> None:
        self.status = status
        if detail is not None:
            self.status_detail = detail

    def end(self, end_mono: Optional[float] = None) -> None:
        """Close the span (idempotent) and hand it to the collector."""
        if self.end_mono is not None:
            return
        self.end_mono = time.monotonic() if end_mono is None else end_mono
        if self._collector is not None:
            self._collector.on_end(self)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "start_unix": self.start_unix,
            "start_mono": self.start_mono,
            "end_mono": self.end_mono,
            "duration_s": self.duration_s,
            "status": self.status,
        }
        if self.status_detail:
            d["status_detail"] = self.status_detail
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = [
                {"offset_s": off, "name": name,
                 **({"attrs": attrs} if attrs else {})}
                for off, name, attrs in self.events
            ]
        if self.root:
            d["root"] = True
        return d

    @staticmethod
    def from_dict(d: dict) -> "Span":
        span = Span(
            name=d.get("name", ""),
            trace_id=d.get("trace_id", ""),
            span_id=d.get("span_id", ""),
            parent_span_id=d.get("parent_span_id"),
            start_mono=float(d.get("start_mono", 0.0)),
            end_mono=d.get("end_mono"),
            start_unix=float(d.get("start_unix", 0.0)),
            attrs=dict(d.get("attrs") or {}),
            status=d.get("status", STATUS_OK),
            status_detail=d.get("status_detail"),
            root=bool(d.get("root", False)),
        )
        for ev in d.get("events") or []:
            span.events.append(
                (float(ev.get("offset_s", 0.0)), ev.get("name", ""),
                 ev.get("attrs"))
            )
        return span
