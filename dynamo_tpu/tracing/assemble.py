"""Offline trace assembly: join per-process span JSONL into per-trace
timelines with a stage-breakdown summary.

Each process exports its own spans (frontend, router, workers); this module
reassembles them by ``trace_id`` and lays them on one wall-clock timeline
using the ``start_unix`` anchor each span carries (monotonic clocks do not
compare across processes; wall clocks do, to NTP precision — good enough
for millisecond-scale serving stages).

CLI (also ``python -m dynamo_tpu.tracing``)::

    python -m dynamo_tpu.tracing.assemble front.jsonl worker-*.jsonl
    python -m dynamo_tpu.tracing.assemble front.jsonl --trace-id 4bf9...
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional


def load_spans(paths: Iterable[str]) -> List[dict]:
    """Read span dicts from JSONL files, deduplicating by (trace, span) id —
    the slow-dump path can export a span twice when both the frontend and
    worker roots of one trace run long."""
    seen = set()
    spans: List[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                key = (d.get("trace_id"), d.get("span_id"))
                if key in seen:
                    continue
                seen.add(key)
                spans.append(d)
    return spans


def group_traces(spans: Iterable[dict]) -> Dict[str, List[dict]]:
    """trace_id → spans sorted by wall-clock start."""
    out: Dict[str, List[dict]] = {}
    for s in spans:
        out.setdefault(s.get("trace_id", "?"), []).append(s)
    for tid in out:
        out[tid].sort(key=lambda s: s.get("start_unix", 0.0))
    return out


def stage_breakdown(spans: Iterable[dict]) -> Dict[str, dict]:
    """Per-stage (span name) duration aggregates for one trace."""
    out: Dict[str, dict] = {}
    for s in spans:
        dur = s.get("duration_s")
        if dur is None:
            continue
        agg = out.setdefault(
            s["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
        )
        agg["count"] += 1
        agg["total_s"] += dur
        agg["max_s"] = max(agg["max_s"], dur)
    return out


def stage_percentiles(spans: Iterable[dict]) -> Dict[str, dict]:
    """Per-stage (span name) duration percentiles across ALL traces in a
    span dump — the offline half of the replay scoreboard's TTFT
    cross-check: client-measured latencies should bracket the
    queue+prefill stage timings reported here."""
    durs: Dict[str, List[float]] = {}
    for s in spans:
        dur = s.get("duration_s")
        if dur is None:
            continue
        durs.setdefault(s["name"], []).append(dur)

    def pct(vals: List[float], q: float) -> float:
        vals = sorted(vals)
        idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
        return vals[idx]

    return {
        name: {
            "count": len(vals),
            "p50_ms": round(pct(vals, 0.50) * 1e3, 3),
            "p99_ms": round(pct(vals, 0.99) * 1e3, 3),
            "max_ms": round(max(vals) * 1e3, 3),
            "total_s": round(sum(vals), 6),
        }
        for name, vals in sorted(durs.items())
    }


def render_summary(stages: Dict[str, dict]) -> str:
    lines = [f"{'stage':<24} {'count':>6} {'p50 ms':>10} {'p99 ms':>10} "
             f"{'max ms':>10}"]
    for name, agg in sorted(stages.items(),
                            key=lambda kv: -kv[1]["total_s"]):
        lines.append(
            f"{name:<24} {agg['count']:>6} {agg['p50_ms']:>10.3f} "
            f"{agg['p99_ms']:>10.3f} {agg['max_ms']:>10.3f}"
        )
    return "\n".join(lines)


def assemble_trace(spans: List[dict]) -> dict:
    """One trace's spans → {trace_id, duration_s, spans, stages}.

    ``duration_s`` is the wall-clock envelope (earliest start to latest
    end); spans come back sorted by start with a ``depth`` field from the
    parent chain for indentation."""
    spans = sorted(spans, key=lambda s: s.get("start_unix", 0.0))
    by_id = {s.get("span_id"): s for s in spans}
    t0 = min((s.get("start_unix", 0.0) for s in spans), default=0.0)

    def depth(s: dict) -> int:
        d = 0
        cur = s
        while cur is not None and d < 32:  # cycle guard
            pid = cur.get("parent_span_id")
            cur = by_id.get(pid) if pid else None
            if cur is not None:
                d += 1
        return d

    t_end = t0
    out_spans = []
    for s in spans:
        dur = s.get("duration_s") or 0.0
        start_rel = s.get("start_unix", t0) - t0
        t_end = max(t_end, s.get("start_unix", t0) + dur)
        out_spans.append({**s, "depth": depth(s), "start_rel_s": start_rel})
    return {
        "trace_id": spans[0].get("trace_id") if spans else None,
        "duration_s": t_end - t0,
        "num_spans": len(spans),
        "spans": out_spans,
        "stages": stage_breakdown(spans),
    }


def render_trace(assembled: dict) -> str:
    """Human-readable indented timeline of one assembled trace."""
    lines = [
        f"trace {assembled['trace_id']}  "
        f"({assembled['num_spans']} spans, "
        f"{assembled['duration_s'] * 1000:.1f} ms)"
    ]
    for s in assembled["spans"]:
        dur = s.get("duration_s")
        dur_txt = f"{dur * 1000:8.2f} ms" if dur is not None else "   open    "
        status = "" if s.get("status", "ok") == "ok" else f"  [{s['status']}]"
        attrs = s.get("attrs") or {}
        attr_txt = ("  " + " ".join(f"{k}={v}" for k, v in attrs.items())
                    if attrs else "")
        lines.append(
            f"  {s.get('start_rel_s', 0.0) * 1000:9.2f} ms  {dur_txt}  "
            f"{'  ' * s.get('depth', 0)}{s['name']}{status}{attr_txt}"
        )
    lines.append("  stage breakdown:")
    for name, agg in sorted(assembled["stages"].items(),
                            key=lambda kv: -kv[1]["total_s"]):
        lines.append(
            f"    {name:<24} x{agg['count']:<3} "
            f"total {agg['total_s'] * 1000:8.2f} ms  "
            f"max {agg['max_s'] * 1000:8.2f} ms"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.tracing",
        description="Assemble per-process span JSONL into per-trace "
                    "timelines with stage breakdowns.",
    )
    p.add_argument("files", nargs="+", help="span JSONL files")
    p.add_argument("--trace-id", default=None,
                   help="only this trace (default: all, newest last)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit assembled traces as JSON instead of text")
    p.add_argument("--summary", action="store_true",
                   help="per-stage p50/p99 across all traces instead of "
                        "per-trace timelines")
    args = p.parse_args(argv)

    if args.summary:
        stages = stage_percentiles(load_spans(args.files))
        if args.as_json:
            print(json.dumps(stages))
        else:
            print(render_summary(stages))
        return 0

    traces = group_traces(load_spans(args.files))
    if args.trace_id is not None:
        if args.trace_id not in traces:
            print(f"trace {args.trace_id} not found", file=sys.stderr)
            return 1
        traces = {args.trace_id: traces[args.trace_id]}

    ordered = sorted(
        traces.items(),
        key=lambda kv: min(s.get("start_unix", 0.0) for s in kv[1]),
    )
    for i, (tid, spans) in enumerate(ordered):
        assembled = assemble_trace(spans)
        if args.as_json:
            print(json.dumps(assembled))
        else:
            if i:
                print()
            print(render_trace(assembled))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
