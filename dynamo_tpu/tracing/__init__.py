"""Distributed request tracing: timed spans over the existing
``traceparent`` propagation, per-stage latency attribution, trace export.

The span model lives in :mod:`.span`, the process-global collector (ring
buffer + sampling + slow-request auto-dump) in :mod:`.collector`, the
JSONL / in-memory / Prometheus sinks in :mod:`.export`, and the offline
per-trace assembler (also a CLI: ``python -m dynamo_tpu.tracing``) in
:mod:`.assemble`.

Stage names instrumented across the serving path::

    frontend.request      root span of one HTTP request
    frontend.admission    admission-controller queue wait
    frontend.tokenize     template render + tokenization
    migration.attempt     one issue of the request to the cluster
    migration.backoff     retry backoff sleep
    router.select         KV-router score + select
    transport.send        client push → first response frame
    worker.ingress        worker-side root: request arrival → stream done
    worker.queue          engine admission → first scheduled chunk
    engine.prefill        first scheduled chunk → first token
    engine.decode         first token → stream end
"""

from .collector import (
    SpanCollector, configure, get_tracer, reset, trace_span,
)
from .export import InMemorySpanExporter, JsonlSpanExporter
from .span import Span

__all__ = [
    "InMemorySpanExporter",
    "JsonlSpanExporter",
    "Span",
    "SpanCollector",
    "configure",
    "get_tracer",
    "reset",
    "trace_span",
]
