"""The in-process span collector: ring buffer, sampling, sinks.

One process-global :class:`SpanCollector` receives every ended span. The hot
path is a single ``deque.append`` (GIL-atomic — no lock) into a bounded ring
buffer; everything else happens per span end, not per token:

- per-stage latency histograms (``stage_latency_seconds{stage=...}``) are
  observed into every attached :class:`MetricsRegistry` for EVERY span,
  sampled or not — aggregates must never depend on the sampling knob;
- head sampling by trace id (deterministic xxh3 hash, so every process in
  the cluster makes the same keep/drop decision for a trace without any
  coordination) gates the span exporters (JSONL / in-memory);
- slow-request auto-dump: when a *root* span (frontend request or worker
  ingress) ends over ``slow_threshold_s`` and its trace was not sampled,
  the whole trace is scraped out of the ring buffer and exported anyway —
  the pathological tail is visible even at sample_ratio 0.
"""

from __future__ import annotations

import secrets
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, Iterator, List, Optional

import xxhash

from .span import STATUS_ERROR, Span

DEFAULT_BUFFER_SIZE = 4096


class SpanCollector:
    """Mints, buffers, samples, and exports spans for one process."""

    def __init__(
        self,
        *,
        sample_ratio: float = 0.0,
        slow_threshold_s: Optional[float] = None,
        buffer_size: int = DEFAULT_BUFFER_SIZE,
        sample_salt: int = 0,
    ):
        self.sample_ratio = sample_ratio
        self.slow_threshold_s = slow_threshold_s
        self.sample_salt = sample_salt
        self._ring: Deque[Span] = deque(maxlen=buffer_size)
        self._exporters: List[Any] = []
        # always-on metric sinks, keyed by id(registry) so frontend and
        # runtime registries coexisting in one process each get their own
        # stage_latency_seconds family
        self._metrics: Dict[int, Any] = {}

    # ------------------------- configuration ---------------------------

    def configure(
        self,
        *,
        sample_ratio: Optional[float] = None,
        slow_threshold_s: Optional[float] = None,
        buffer_size: Optional[int] = None,
        sample_salt: Optional[int] = None,
    ) -> "SpanCollector":
        if sample_ratio is not None:
            self.sample_ratio = sample_ratio
        if slow_threshold_s is not None:
            # 0 and negatives mean "disabled" so config files can express it
            self.slow_threshold_s = (
                slow_threshold_s if slow_threshold_s > 0 else None
            )
        if sample_salt is not None:
            self.sample_salt = sample_salt
        if buffer_size is not None and buffer_size != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=max(1, buffer_size))
        return self

    def add_exporter(self, exporter: Any) -> None:
        if exporter not in self._exporters:
            self._exporters.append(exporter)

    def remove_exporter(self, exporter: Any) -> None:
        if exporter in self._exporters:
            self._exporters.remove(exporter)

    def add_jsonl(self, path: str) -> None:
        """Add a JSONL exporter for ``path`` unless one already writes there
        (several runtimes in one process share a config file)."""
        from .export import JsonlSpanExporter

        for e in self._exporters:
            if isinstance(e, JsonlSpanExporter) and e.path == path:
                return
        self.add_exporter(JsonlSpanExporter(path))

    def attach_metrics(self, registry: Any,
                       name: str = "stage_latency_seconds") -> None:
        """Mint the per-stage latency histogram on ``registry`` and observe
        every span's duration into it (idempotent per registry)."""
        from .export import MetricsSpanExporter

        key = id(registry)
        if key not in self._metrics:
            self._metrics[key] = MetricsSpanExporter(registry, name=name)

    def detach_metrics(self, registry: Any) -> None:
        self._metrics.pop(id(registry), None)

    # --------------------------- sampling ------------------------------

    def sampled(self, trace_id: str) -> bool:
        """Deterministic head-sampling decision for a trace id: the same id
        and salt hash identically in every process, so a trace is either
        exported everywhere or nowhere."""
        if self.sample_ratio <= 0:
            return False
        if self.sample_ratio >= 1:
            return True
        h = xxhash.xxh3_64_intdigest(trace_id, seed=self.sample_salt)
        return h / 2.0 ** 64 < self.sample_ratio

    # --------------------------- span minting --------------------------

    def start_span(
        self,
        name: str,
        context: Any = None,
        *,
        trace: Any = None,
        parent_span_id: Optional[str] = None,
        attrs: Optional[dict] = None,
        root: bool = False,
    ) -> Span:
        """Open a span.

        Two parenting forms:
        - ``trace=``: the span ADOPTS that :class:`TraceContext`'s span id as
          its own (the id is already on the wire / on the context, so work
          attributed to it downstream parents correctly); pass
          ``parent_span_id`` explicitly.
        - ``context=``: a fresh span id is minted under
          ``context.trace.span_id`` — the usual "sub-operation of this
          request" form.
        """
        if trace is not None:
            trace_id, span_id = trace.trace_id, trace.span_id
            parent = parent_span_id
        elif context is not None and getattr(context, "trace", None) is not None:
            trace_id = context.trace.trace_id
            parent = parent_span_id or context.trace.span_id
            span_id = secrets.token_hex(8)
        else:
            trace_id = secrets.token_hex(16)
            span_id = secrets.token_hex(8)
            parent = parent_span_id
        return Span(
            name=name,
            trace_id=trace_id,
            span_id=span_id,
            parent_span_id=parent,
            start_mono=time.monotonic(),
            start_unix=time.time(),
            attrs=dict(attrs or {}),
            root=root,
            _collector=self,
        )

    def record(
        self,
        name: str,
        context: Any = None,
        *,
        start_mono: float,
        end_mono: float,
        trace: Any = None,
        parent_span_id: Optional[str] = None,
        attrs: Optional[dict] = None,
        status: str = "ok",
        status_detail: Optional[str] = None,
        root: bool = False,
    ) -> Span:
        """Record an already-elapsed window from explicit monotonic stamps —
        the engine hot path stamps floats per sequence and attributes the
        queue/prefill/decode windows once, after the stream ends, instead of
        carrying live span objects per token."""
        span = self.start_span(
            name, context, trace=trace, parent_span_id=parent_span_id,
            attrs=attrs, root=root,
        )
        span.start_mono = start_mono
        # re-derive the wall anchor for the actual start moment
        span.start_unix = time.time() - (time.monotonic() - start_mono)
        span.status = status
        span.status_detail = status_detail
        span.end(end_mono)
        return span

    @contextmanager
    def trace_span(
        self,
        name: str,
        context: Any = None,
        *,
        trace: Any = None,
        parent_span_id: Optional[str] = None,
        attrs: Optional[dict] = None,
        root: bool = False,
    ) -> Iterator[Span]:
        span = self.start_span(
            name, context, trace=trace, parent_span_id=parent_span_id,
            attrs=attrs, root=root,
        )
        try:
            yield span
        except BaseException as e:
            span.set_status(STATUS_ERROR, repr(e))
            raise
        finally:
            span.end()

    # ----------------------------- sinks -------------------------------

    def on_end(self, span: Span) -> None:
        self._ring.append(span)
        for sink in self._metrics.values():
            sink.export(span)
        if not self._exporters:
            return
        if self.sampled(span.trace_id):
            for e in self._exporters:
                e.export(span)
        elif (span.root and self.slow_threshold_s is not None
              and (span.duration_s or 0.0) >= self.slow_threshold_s):
            # slow-request auto-dump: the trace was not head-sampled, but
            # this root ran long — flush everything the ring still holds
            # for it (children ended before their root, so they are here)
            for s in self.get_trace(span.trace_id):
                for e in self._exporters:
                    e.export(s)

    # ---------------------------- queries ------------------------------

    def get_trace(self, trace_id: str) -> List[Span]:
        """All buffered spans of a trace, oldest first."""
        return [s for s in list(self._ring) if s.trace_id == trace_id]

    def trace_ids(self, limit: int = 50) -> List[str]:
        """Most recently seen trace ids, newest first, deduplicated."""
        seen: List[str] = []
        for s in reversed(list(self._ring)):
            if s.trace_id not in seen:
                seen.append(s.trace_id)
                if len(seen) >= limit:
                    break
        return seen


# -------------------------- process-global API --------------------------

_collector = SpanCollector()


def get_tracer() -> SpanCollector:
    return _collector


def configure(**kwargs: Any) -> SpanCollector:
    return _collector.configure(**kwargs)


def reset() -> SpanCollector:
    """Replace the global collector (tests: isolate exporters/sampling)."""
    global _collector
    _collector = SpanCollector()
    return _collector


@contextmanager
def trace_span(name: str, context: Any = None, **kwargs: Any) -> Iterator[Span]:
    with _collector.trace_span(name, context, **kwargs) as span:
        yield span
