"""Span exporters: JSONL for offline assembly, in-memory for tests,
Prometheus histograms for always-on per-stage latency aggregates."""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from .span import Span


class InMemorySpanExporter:
    """Keeps exported spans in a list — the test sink."""

    def __init__(self):
        self.spans: List[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def by_trace(self) -> Dict[str, List[Span]]:
        out: Dict[str, List[Span]] = {}
        for s in self.spans:
            out.setdefault(s.trace_id, []).append(s)
        return out

    def clear(self) -> None:
        self.spans.clear()


class JsonlSpanExporter:
    """One JSON object per line, flushed per span so a crashing process
    loses at most the span being written. Open lazily: a configured-but-idle
    exporter never touches the filesystem."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), separators=(",", ":"))
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class MetricsSpanExporter:
    """Observes every span's duration into
    ``stage_latency_seconds{stage=<span name>}`` on a MetricsRegistry
    (LATENCY_BUCKETS by default — same buckets as TTFT/ITL).

    Flight-recorder attributes the engine stamps on decode spans (``mfu``,
    ``goodput_tok_s``, ``padding_waste_ratio``) additionally surface as
    ``stage_obs{stage,attr}`` gauges — the per-request view of the live
    recorder, without a second instrumentation path."""

    OBS_ATTRS = ("mfu", "goodput_tok_s", "padding_waste_ratio")

    def __init__(self, registry, name: str = "stage_latency_seconds"):
        self._hist = registry.histogram(
            name, "per-stage latency attributed from trace spans", ["stage"]
        )
        self._g_obs = registry.gauge(
            "stage_obs",
            "flight-recorder attributes carried on stage spans "
            "(last exported span wins)", ["stage", "attr"]
        )

    def export(self, span: Span) -> None:
        dur: Optional[float] = span.duration_s
        if dur is not None:
            self._hist.labels(stage=span.name).observe(max(dur, 0.0))
        attrs = span.attrs or {}
        for key in self.OBS_ATTRS:
            val = attrs.get(key)
            if isinstance(val, (int, float)):
                self._g_obs.labels(stage=span.name, attr=key).set(val)
