"""Pipeline-parallel SERVING step: the engine's unified prefill/decode
step executed GPipe-style over a ``pp`` mesh axis, with the paged KV cache
stage-sharded by layer.

Layout (all decided by shardings, not code):

- stacked layer params ``[L, ...]`` sharded ``P("pp")`` — stage s owns
  layers ``[s*L/S, (s+1)*L/S)``;
- the paged cache is STACKED ``[L, NB, KV, bs, hd]`` (unlike the
  single-host engine's per-layer list) and sharded ``P("pp")`` on L, so
  each stage scatter-updates only its own layers' blocks in place;
- embed / final_norm / lm_head replicate: embedding and sampling are tiny
  next to the layer stack, and replicating them avoids edge hops.

Schedule: classic GPipe over the BATCH axis — B rows split into M
microbatches, activations hop stage→stage via ``lax.ppermute`` (neighbor
ICI/DCN traffic, one ``[mb, T, D]`` tensor per boundary per tick), bubble
fraction (S-1)/(S+M-1). Decode batches (B up to max_num_seqs) microbatch
well; a single-sequence prefill chunk runs M=1 (full bubble) — prefill
overlap comes from the engine interleaving chunked prefills with decode
batches, the same interleaving it already does.

Correctness notes: bubble ticks scatter to the trash block (index 0) so
they can never touch live cache; the causal order within a sequence holds
because each stage processes microbatches in order (the skew only offsets
WHICH tick a microbatch is processed at, never reorders them).

Same signature as ``model.raw_step_fn`` so the engine swaps it in
untouched. SURVEY §2.3 PP; the reference passes --pipeline-parallel-size
through to its engines — here the schedule is ours.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..engine.config import EngineConfig, ModelConfig
from ..engine import model as model_lib
from . import layout
from .layout import AXIS_PP, make_axes_mesh

Cache = dict


def make_pp_mesh(num_stages: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    return make_axes_mesh((num_stages,), (AXIS_PP,),
                          devices=devices[:num_stages])


def init_pp_cache(cfg: ModelConfig, eng: EngineConfig) -> Cache:
    """Stacked paged cache [L, NB, KV, bs, hd] (stage-shardable on L)."""
    dt = jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, eng.num_blocks, cfg.num_kv_heads,
             eng.block_size, cfg.head_dim_)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def pp_cache_shardings(mesh: Mesh, cfg: ModelConfig) -> Cache:
    stage = layout.named(mesh, AXIS_PP)
    return {"k": stage, "v": stage}


def pp_param_shardings(mesh: Mesh, cfg: ModelConfig):
    """Layer stack over pp; everything else replicated."""
    stage = layout.named(mesh, AXIS_PP)
    repl = layout.replicated(mesh)

    layer_names = ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm"]
    layer_names += (["w_router", "w_gate", "w_up", "w_down"]
                    if cfg.is_moe else ["w_gate", "w_up", "w_down"])
    shardings = {
        "embed": repl,
        "layers": {name: stage for name in layer_names},
        "final_norm": repl,
    }
    if not cfg.tie_word_embeddings:
        shardings["lm_head"] = repl
    return shardings


def _stage_layers(cfg: ModelConfig, eng: EngineConfig, Lp: int,
                  stage_params, lk, lv, h, positions, block_tables,
                  scatter_block, scatter_off):
    """Apply this stage's Lp layers over one microbatch chunk.

    h [mb, T, D]; lk/lv [Lp, NB, KV, bs, hd] (functionally updated).
    The attention path is the gathered-context einsum — inside shard_map
    every stage attends over its own layers' full context."""
    B, T = h.shape[0], h.shape[1]
    bs = eng.block_size
    hd = cfg.head_dim_
    H, KV = cfg.num_heads, cfg.num_kv_heads
    W = block_tables.shape[1]

    for li in range(Lp):
        p = {name: w[li] for name, w in stage_params.items()}
        x = model_lib._rms_norm(h, p["attn_norm"], cfg.rms_norm_eps)
        q = (x @ p["wq"]).reshape(B, T, H, hd)
        k = (x @ p["wk"]).reshape(B, T, KV, hd)
        v = (x @ p["wv"]).reshape(B, T, KV, hd)
        q = model_lib._rope(q, positions, cfg.rope_theta)
        k = model_lib._rope(k, positions, cfg.rope_theta)
        layer_k = lk[li].at[scatter_block, :, scatter_off].set(
            k.reshape(B * T, KV, hd)
        )
        layer_v = lv[li].at[scatter_block, :, scatter_off].set(
            v.reshape(B * T, KV, hd)
        )
        k_all = jnp.take(
            layer_k, block_tables.reshape(-1), axis=0
        ).reshape(B, W, KV, bs, hd).transpose(0, 1, 3, 2, 4).reshape(
            B, W * bs, KV, hd)
        v_all = jnp.take(
            layer_v, block_tables.reshape(-1), axis=0
        ).reshape(B, W, KV, bs, hd).transpose(0, 1, 3, 2, 4).reshape(
            B, W * bs, KV, hd)
        attn = model_lib._attention(q, k_all, v_all, positions)
        h = h + attn.reshape(B, T, H * hd) @ p["wo"]
        x = model_lib._rms_norm(h, p["mlp_norm"], cfg.rms_norm_eps)
        if cfg.is_moe:
            from .moe import moe_ffn

            D = x.shape[-1]
            out = moe_ffn(
                x.reshape(B * T, D),
                p["w_router"], p["w_gate"], p["w_up"], p["w_down"],
                top_k=cfg.num_experts_per_token,
                capacity_factor=cfg.moe_capacity_factor,
            )
            h = h + out.reshape(B, T, D)
        else:
            gate = jax.nn.silu((x @ p["w_gate"]).astype(jnp.float32))
            up = (x @ p["w_up"]).astype(jnp.float32)
            h = h + ((gate * up).astype(h.dtype) @ p["w_down"])
        lk = lk.at[li].set(layer_k)
        lv = lv.at[li].set(layer_v)
    return h, lk, lv


def raw_pp_step_fn(cfg: ModelConfig, eng: EngineConfig, mesh: Mesh,
                   num_microbatches: int = 4):
    """The pipelined unified step (same signature as raw_step_fn)."""
    S = mesh.shape[AXIS_PP]
    if cfg.num_layers % S != 0:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pp={S}"
        )
    Lp = cfg.num_layers // S

    def step(params, cache, tokens, positions, block_tables,
             last_idx, rng, temperature, top_k, top_p, seeds):
        B, T = tokens.shape
        M = num_microbatches
        while B % M != 0:   # bucketed B is pow2; clamp M to divide it
            M //= 2
        mb = B // M
        bs = eng.block_size
        W = block_tables.shape[1]

        h0 = jnp.take(params["embed"], tokens, axis=0)   # [B, T, D]
        D = h0.shape[-1]
        h_mb = h0.reshape(M, mb, T, D)
        pos_mb = positions.reshape(M, mb, T)
        tbl_mb = block_tables.reshape(M, mb, W)

        def body(stage_params, ck, cv, h_all, pos_all, tbl_all):
            stage = jax.lax.axis_index(AXIS_PP)
            fwd = [(j, (j + 1) % S) for j in range(S)]
            lk, lv = ck, cv                          # [Lp, NB, KV, bs, hd]
            act = jnp.zeros_like(h_all[0])
            out = jnp.zeros_like(h_all)
            for t in range(M + S - 1):
                feed = h_all[t] if t < M else jnp.zeros_like(h_all[0])
                act = jnp.where(stage == 0, feed, act)
                mb_idx = jnp.clip(t - stage, 0, M - 1)
                valid = ((t - stage) >= 0) & ((t - stage) < M)
                pos = jnp.take(pos_all, mb_idx, axis=0)     # [mb, T]
                tbl = jnp.take(tbl_all, mb_idx, axis=0)     # [mb, W]
                # bubble ticks must not touch live cache: only valid
                # in-window microbatches with real positions scatter
                pos_safe = jnp.maximum(pos, 0)
                logical = pos_safe // bs
                phys = jnp.take_along_axis(
                    tbl, jnp.minimum(logical, W - 1), axis=1
                )
                live = valid & (pos >= 0)
                scatter_block = jnp.where(live, phys, 0).reshape(-1)
                scatter_off = jnp.where(live, pos_safe % bs, 0).reshape(-1)
                y, lk, lv = _stage_layers(
                    cfg, eng, Lp, stage_params, lk, lv, act, pos, tbl,
                    scatter_block, scatter_off,
                )
                act = jnp.where(valid, y, act)
                bank = (stage == S - 1) & valid
                sel = (jnp.arange(M) == jnp.clip(t - stage, 0, M - 1))[
                    (slice(None),) + (None,) * (out.ndim - 1)
                ]
                out = jnp.where(bank & sel, act[None], out)
                if t != M + S - 2:
                    act = jax.lax.ppermute(act, AXIS_PP, fwd)
            out = jnp.where(stage == S - 1, out, jnp.zeros_like(out))
            return jax.lax.psum(out, AXIS_PP), lk, lv

        stage_spec = layout.spec(AXIS_PP)
        repl_spec = layout.spec()
        h_out, new_k, new_v = layout.shard_map(
            body, mesh=mesh,
            in_specs=(
                jax.tree.map(lambda _: stage_spec, params["layers"]),
                stage_spec, stage_spec, repl_spec, repl_spec, repl_spec,
            ),
            out_specs=(repl_spec, stage_spec, stage_spec),
        )(params["layers"], cache["k"], cache["v"], h_mb, pos_mb, tbl_mb)

        h = h_out.reshape(B, T, D)
        h = model_lib._rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
        h_last = h[jnp.arange(B), last_idx]
        logits = model_lib.logits_fn(cfg, params, h_last)
        pos_last = jnp.take_along_axis(
            positions, last_idx[:, None], axis=1
        )[:, 0]
        sampled = model_lib.sample(
            logits, rng, temperature, top_k, top_p, seeds, pos_last
        )
        return {"k": new_k, "v": new_v}, sampled

    return step


def make_pp_step_fn(cfg: ModelConfig, eng: EngineConfig, mesh: Mesh,
                    num_microbatches: int = 4):
    return jax.jit(
        raw_pp_step_fn(cfg, eng, mesh, num_microbatches),
        donate_argnums=(1,),
    )
