"""Multi-host serving bring-up: jax.distributed + step-plan replication.

The reference reaches multi-node scale by delegating model parallelism to
its engines and rendezvousing workers over an etcd barrier (ref:
lib/runtime/src/utils/leader_worker_barrier.rs:125,218; sglang multinode
flags in components/backends/sglang/docs/dsr1-wideep-h100.md:65-121). Here
the engine is ours, so multi-host IS the engine's problem, and the
TPU-native shape is multi-controller SPMD:

- every host process calls ``jax.distributed.initialize`` (coordinator =
  host 0), after which ``jax.devices()`` is the global chip list and one
  ``Mesh`` spans the slice;
- every process must issue the SAME jitted calls in the same order with
  the same (replicated) host inputs. Scheduling happens once, on host 0
  (the leader); followers replay the leader's step plans.

Plan replication rides the runtime's TCP response-stream transport: each
follower opens one long-lived request to the leader's ``step_stream``
endpoint and the leader streams one plan per executed step — TCP gives
ordering and reliability, and the store is not on the per-step path.
Bring-up is gated by the store barrier (``runtime/barrier.py``): the
leader serves ``step_stream``, waits for every follower to connect, and
only then registers the model and starts accepting traffic.

Step plans carry the small host-side batch arrays (token ids, positions,
block tables — a few KB); model state (params, paged KV cache) never
moves: it lives sharded across the slice and is updated in place by the
replayed steps. RNG stays in sync because every process derives the same
key sequence from the same seed, one split per step.

Scope note: disagg KV extract/inject and KVBM host offload are
single-host features today — a multi-host worker serves the aggregated
path (the reference's multinode recipes are likewise aggregated
tensor-parallel serving per worker group).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, Optional

import jax
import numpy as np

from ..runtime.barrier import LeaderBarrier, WorkerBarrier
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..utils.logging import get_logger

log = get_logger("multihost")


@dataclass
class MultihostConfig:
    coordinator: Optional[str] = None   # "host0:port"
    num_hosts: int = 1
    host_index: int = 0
    barrier_timeout_s: float = 300.0
    # failure detection on the step stream: the leader emits a heartbeat
    # per idle interval; a follower that sees NOTHING (plans or beats) for
    # heartbeat_timeout_s declares the leader dead and exits so the
    # supervisor restarts the group. (SPMD over one mesh cannot re-elect:
    # a surviving subset would deadlock in collectives missing the dead
    # host's devices — fast detection + group restart IS the failover.)
    heartbeat_interval_s: float = 2.0
    heartbeat_timeout_s: float = 10.0

    @property
    def enabled(self) -> bool:
        return self.num_hosts > 1

    @property
    def is_leader(self) -> bool:
        return self.host_index == 0


def initialize_distributed(cfg: MultihostConfig) -> bool:
    """Join the multi-controller runtime. Must run before any other JAX
    call (backend init). Returns True when distributed mode is active."""
    if not cfg.enabled:
        return False
    if not cfg.coordinator:
        raise ValueError("--coordinator is required when --num-hosts > 1")
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_hosts,
        process_id=cfg.host_index,
    )
    log.info(
        "joined distributed runtime: process %d/%d, %d global devices",
        cfg.host_index, cfg.num_hosts, len(jax.devices()),
    )
    return True


# --------------------------- plan encoding -------------------------------


def encode_plan(kind: str, arrays: Dict[str, np.ndarray]) -> dict:
    from ..multimodal.encoder import array_to_wire

    return {"k": kind,
            "a": {n: array_to_wire(v) for n, v in arrays.items()}}


def decode_plan(plan: dict):
    from ..multimodal.encoder import array_from_wire

    return plan["k"], {n: array_from_wire(v) for n, v in plan["a"].items()}


# ------------------------------ leader -----------------------------------


class StepBroadcaster:
    """Fans executed step plans out to connected followers.

    ``sink`` is installed as the engine's ``step_sink`` and is called on
    the engine's step-executor thread; delivery hops to the event loop.
    """

    # a follower this many plans behind is wedged (its TCP connection is
    # open but nothing drains); unbounded buffering would eat the leader
    MAX_LAG = 10_000

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        self._loop = loop or asyncio.get_event_loop()
        self._queues: Dict[int, asyncio.Queue] = {}
        self.num_plans = 0
        self.num_dropped_followers = 0

    def sink(self, kind: str, arrays: Dict[str, np.ndarray]) -> None:
        plan = encode_plan(kind, arrays)
        self._loop.call_soon_threadsafe(self._fanout, plan)

    def _fanout(self, plan: dict) -> None:
        self.num_plans += 1
        for host, q in list(self._queues.items()):
            if q.qsize() > self.MAX_LAG:
                log.error("follower %d wedged (%d plans behind) — dropping"
                          " it; the group must restart", host, q.qsize())
                self.unsubscribe(host)
                self.num_dropped_followers += 1
                # the handler may be parked in the socket send (that IS the
                # wedge) and won't drain this queue — clear it so the
                # backlog is freed NOW; the sentinel is then next in line
                # when TCP eventually errors the connection and the handler
                # resumes (or closes via its finally)
                while not q.empty():
                    q.get_nowait()
                q.put_nowait({"closed": True})
                continue
            q.put_nowait(plan)

    def subscribe(self, host_index: int) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._queues[host_index] = q
        return q

    def unsubscribe(self, host_index: int,
                    queue: Optional[asyncio.Queue] = None) -> None:
        """With ``queue`` given, only remove if THAT queue is still the
        registered one — a stale handler's teardown (wedged socket finally
        erroring out) must not evict a restarted follower's fresh
        subscription, which would starve it of plans while heartbeats keep
        it looking alive."""
        if queue is None or self._queues.get(host_index) is queue:
            self._queues.pop(host_index, None)

    @property
    def num_followers(self) -> int:
        return len(self._queues)


class StepStreamHandler(AsyncEngine):
    """Leader endpoint: one long-lived stream of step plans per follower.

    Idle gaps are filled with heartbeats so followers can distinguish "no
    traffic" from "leader dead behind an open TCP connection" (a SIGKILLed
    process closes its sockets; a dead HOST or partition does not)."""

    def __init__(self, broadcaster: StepBroadcaster,
                 heartbeat_interval_s: float = 2.0):
        self.broadcaster = broadcaster
        self.heartbeat_interval_s = heartbeat_interval_s

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        host_index = int(request["host_index"])
        queue = self.broadcaster.subscribe(host_index)
        log.info("follower %d connected to step stream", host_index)
        try:
            yield {"hello": True}
            while True:
                try:
                    msg = await asyncio.wait_for(
                        queue.get(), timeout=self.heartbeat_interval_s
                    )
                except asyncio.TimeoutError:
                    yield {"hb": True}
                    continue
                yield msg
                if msg.get("closed"):
                    return  # broadcaster dropped this follower
        finally:
            self.broadcaster.unsubscribe(host_index, queue)
            log.warning("follower %d disconnected", host_index)


async def leader_gate(
    store, cfg: MultihostConfig, broadcaster: StepBroadcaster, name: str
) -> None:
    """Barrier: wait until every follower is connected to the step stream
    before the model is registered (no traffic may be scheduled while a
    follower is still joining — it would miss plans and diverge)."""
    barrier = LeaderBarrier(
        f"multihost/{name}", cfg.num_hosts - 1,
        timeout_s=cfg.barrier_timeout_s,
    )
    await barrier.sync(store, {"model": name, "num_hosts": cfg.num_hosts})
    if broadcaster.num_followers != cfg.num_hosts - 1:
        raise RuntimeError(
            f"barrier passed but only {broadcaster.num_followers}/"
            f"{cfg.num_hosts - 1} followers on the step stream"
        )
    log.info("multihost bring-up complete: %d followers", cfg.num_hosts - 1)


# ------------------------------ follower ---------------------------------


def replay_plan(engine, kind: str, arrays: Dict[str, np.ndarray]) -> None:
    """Execute one leader plan. MUST run on the engine's step-executor
    thread (cache donation discipline); consumes RNG exactly as the
    leader's execution path did. Ring ops ("rp"/"rsp"/"w") thread the
    follower's own last_tok buffer — it evolves identically to the
    leader's because every input that feeds it is replayed in order."""
    if kind == "w":
        # autopilot window: zero arrays — the follower's device control
        # state and seat map evolved identically through "ctl"/"cols"
        engine.cache, engine._ctl, _ = engine._ap_window_fn(
            engine.params, engine.cache, engine._ctl,
            engine._ap_rows_dev,
        )
        return
    if kind == "sw":
        # spec draft+verify window: zero arrays, like "w"
        engine.cache, engine._ctl, _ = engine._spec_window_fn(
            engine.params, engine.cache, engine._ctl,
            engine._ap_rows_dev,
        )
        return
    if kind == "sph":
        engine._ctl = engine._spec_hist_fill_fn(
            engine._ctl, arrays["slots"], arrays["hist"]
        )
        return
    if kind == "ctl":
        engine._ctl = engine._ap_delta_fn(
            engine._ctl, arrays["di"], arrays["df"]
        )
        return
    if kind == "cols":
        engine._ap_cols = [int(x) for x in arrays["rows"]]
        engine._ap_rows_dev = jax.device_put(arrays["rows"])
        return
    if kind == "pp":
        from ..engine import model as model_lib

        T, W = (int(x) for x in arrays["tw"])
        fn = engine._packed_prefill_fns.get((T, W))
        if fn is None:
            fn = model_lib.make_packed_prefill_fn(
                engine.model_config, engine.config, T, W, engine.mesh
            )
            engine._packed_prefill_fns[(T, W)] = fn
        engine.cache, new_lt, _ = fn(
            engine.params, engine.cache, engine._ctl["last_tok"],
            arrays["pint"], engine._next_rng(),
        )
        engine._ctl = {**engine._ctl, "last_tok": new_lt}
        return
    B = arrays["temp"].shape[0]
    top_p = arrays.get("top_p", np.ones((B,), np.float32))
    seeds = arrays.get("seeds", np.full((B,), -1, np.int32))
    if kind in ("rsp", "mrp"):
        if kind == "mrp" and engine._mm_ring_fn is None:
            from ..engine import model as model_lib

            engine._mm_ring_fn = model_lib.make_mm_ring_prefill_fn(
                engine.model_config, engine.config, engine.mesh
            )
        extra = ()
        if kind == "mrp":
            extra = (arrays["mm_embeds"],
                     arrays["mm_mask"].astype(bool))
        fn = (engine._sp_prefill_fn if kind == "rsp"
              else engine._mm_ring_fn)
        engine.cache, new_lt, _ = fn(
            engine.params, engine.cache, engine._ctl["last_tok"],
            arrays["tokens"], arrays["positions"], arrays["tables"],
            arrays["last_idx"], arrays["slot"], arrays["write"],
            engine._next_rng(), arrays["temp"], arrays["top_k"],
            top_p, seeds, *extra,
        )
        engine._ctl = {**engine._ctl, "last_tok": new_lt}
    else:  # "p"/"d": the legacy synchronous unified step
        engine.cache, _ = engine._step_fn(
            engine.params, engine.cache, arrays["tokens"],
            arrays["positions"], arrays["tables"], arrays["last_idx"],
            engine._next_rng(), arrays["temp"], arrays["top_k"],
            top_p, seeds,
        )


async def follower_loop(
    runtime, engine, cfg: MultihostConfig, name: str,
    component: str = "backend",
) -> None:
    """Connect to the leader's step stream, pass the barrier, replay plans
    until the stream closes OR goes silent past the heartbeat deadline
    (leader death ⇒ the mesh is gone — exit so the supervisor restarts the
    whole group; a partial group cannot re-elect, see MultihostConfig)."""
    client = await (
        runtime.namespace().component(component).endpoint("step_stream")
        .client()
    )
    await client.wait_for_instances(1, timeout_s=cfg.barrier_timeout_s)
    loop = asyncio.get_running_loop()
    stream = client.round_robin(
        {"host_index": cfg.host_index}, Context()
    ).__aiter__()
    replayed = 0
    while True:
        try:
            msg = await asyncio.wait_for(
                stream.__anext__(), timeout=cfg.heartbeat_timeout_s
            )
        except StopAsyncIteration:
            log.warning("step stream closed after %d plans — leader gone,"
                        " exiting", replayed)
            return
        except asyncio.TimeoutError:
            log.error(
                "no plan or heartbeat for %.0fs after %d plans — leader "
                "presumed dead, exiting", cfg.heartbeat_timeout_s, replayed,
            )
            return
        if msg.get("hb"):
            continue
        if msg.get("closed"):
            log.error("leader dropped this follower (wedged) — exiting")
            return
        if msg.get("hello"):
            await WorkerBarrier(
                f"multihost/{name}", f"host-{cfg.host_index}",
                timeout_s=cfg.barrier_timeout_s,
            ).sync(runtime.store, {"host_index": cfg.host_index})
            log.info("follower %d ready (barrier passed)", cfg.host_index)
            continue
        kind, arrays = decode_plan(msg)
        await loop.run_in_executor(
            engine._executor, replay_plan, engine, kind, arrays
        )
        replayed += 1
        if replayed == 1 or replayed % 1000 == 0:
            log.info("follower %d: %d plans replayed", cfg.host_index,
                     replayed)
