"""Ulysses-style sequence parallelism: all-to-all head scatter.

Alternative to ring attention (SURVEY §5 long-context): instead of rotating
K/V chunks, one ``all_to_all`` re-shards the tensors from sequence-sharded
``[B, T/sp, H, hd]`` to head-sharded ``[B, T, H/sp, hd]``, each device runs
*full-sequence* attention over its head group, and a second ``all_to_all``
restores sequence sharding. Two collectives total (vs sp-1 ppermute hops),
at the cost of requiring ``H % sp == 0`` and full-T activations per device
during attention. Better for moderate T / large sp; ring wins when T is the
memory bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import layout


def _full_attention(q, k, v, causal: bool):
    """Vanilla causal attention, f32 accumulation. q: [B, T, H, hd],
    k/v: [B, T, KV, hd] (GQA: H % KV == 0)."""
    B, T, H, hd = q.shape
    KV = k.shape[2]
    G = H // KV
    qf = q.astype(jnp.float32).reshape(B, T, KV, G, hd)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("btkgh,bskh->btkgs", qf, kf) / np.sqrt(hd)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, :, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("btkgs,bskh->btkgh", p, vf).reshape(B, T, H, hd)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,   # [B, C, H, hd] sequence-sharded (C = T / sp)
    k: jax.Array,   # [B, C, KV, hd]
    v: jax.Array,   # [B, C, KV, hd]
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Per-shard Ulysses body — call inside ``shard_map``.

    Requires ``H % sp == 0`` and ``KV % sp == 0``.
    """
    n = layout.axis_size(axis_name)
    B, C, H, hd = q.shape
    KV = k.shape[2]
    if H % n or KV % n:
        raise ValueError(f"heads ({H}, kv {KV}) must divide sp={n}")

    def seq_to_heads(x):
        # [B, C, Hx, hd] -> [B, n*C, Hx/n, hd]: split heads, all-to-all the
        # head groups against the sequence axis
        Hx = x.shape[2]
        x = x.reshape(B, C, n, Hx // n, hd)
        # concat_axis=1 (sequence), split_axis=2 (head groups)
        x = jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )
        return x.reshape(B, n * C, Hx // n, hd)

    def heads_to_seq(x, Hx):
        # [B, n*C, Hx/n, hd] -> [B, C, Hx, hd]: send sequence chunk j back
        # to device j, gather head groups
        x = x.reshape(B, n, C, Hx // n, hd)
        x = jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=3, tiled=True
        )                     # [B, 1, C, Hx, hd]
        return x.reshape(B, C, Hx, hd)

    qh = seq_to_heads(q)    # [B, T, H/n, hd]
    kh = seq_to_heads(k)    # [B, T, KV/n, hd]
    vh = seq_to_heads(v)
    out = _full_attention(qh, kh, vh, causal)
    return heads_to_seq(out, H)


def make_ulysses_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = True
):
    """Jittable global-array Ulysses attention (same contract as
    ``make_ring_attention``)."""
    fn = functools.partial(ulysses_attention, axis_name=axis, causal=causal)
    spec = layout.spec(None, axis, None, None)
    return jax.jit(layout.shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    ))
