"""Pipeline parallelism: GPipe-style microbatched stage execution over a
``pp`` mesh axis.

The reference passes ``--pipeline-parallel-size`` through to its engines
(SURVEY §2.3); here it is a native building block. Stages are laid out one
per device along ``pp``; activations hop stage→stage via ``lax.ppermute``
(neighbor ICI/DCN traffic only — this is the axis to map onto DCN for
multi-pod, since exactly one activation tensor crosses the boundary per
microbatch per step). Classic GPipe schedule: with S stages and M
microbatches the bubble fraction is (S-1)/(S+M-1).

Contract: ``stage_fn(stage_params, x) -> y`` with ``x``/``y`` the same
shape/dtype (a residual-block stack); ``params`` leaves are stacked on a
leading stage axis sharded ``P("pp", ...)``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from . import layout
from .layout import AXIS_PP


def pipeline_stages(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,     # this device's stage params (leading axis sliced)
    x: jax.Array,          # [M, mb, ...] all microbatches (replicated input)
    axis_name: str = AXIS_PP,
) -> jax.Array:
    """Per-shard pipeline body — call inside ``shard_map``.

    Returns the final-stage outputs ``[M, mb, ...]`` (replicated to every
    stage via a masked psum at the end).
    """
    S = layout.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = x.shape[0]
    fwd = [(j, (j + 1) % S) for j in range(S)]

    act = jnp.zeros_like(x[0])
    out = jnp.zeros_like(x)
    for t in range(M + S - 1):
        # stage 0 ingests microbatch t; everyone else uses the activation
        # handed over by its predecessor last step
        feed = x[t] if t < M else jnp.zeros_like(x[0])
        act = jnp.where(stage == 0, feed, act)
        # microbatch index this stage holds at time t (valid in-window)
        mb = t - stage
        valid = (mb >= 0) & (mb < M)
        y = stage_fn(stage_params, act)
        act = jnp.where(valid, y, act)
        # last stage banks its finished microbatch
        bank = (stage == S - 1) & valid
        out = jnp.where(
            bank & (jnp.arange(M) == jnp.clip(mb, 0, M - 1))[
                (slice(None),) + (None,) * (out.ndim - 1)
            ],
            act[None], out,
        )
        if t != M + S - 2:
            act = jax.lax.ppermute(act, axis_name, fwd)
    # replicate the last stage's banked outputs to all stages
    out = jnp.where(stage == S - 1, out, jnp.zeros_like(out))
    return jax.lax.psum(out, axis_name)


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis: str = AXIS_PP,
):
    """Jittable pipelined forward: ``f(params, x[M, mb, ...]) -> y``.

    ``params`` leaves must carry a leading stage axis of size
    ``mesh.shape[axis]`` (shard with :func:`stage_shardings`).
    """
    fn = functools.partial(pipeline_stages, axis_name=axis)

    def run(params, x):
        return fn(
            stage_fn,
            jax.tree.map(lambda p: p[0], params),  # shard_map slices stage
            x,
        )

    def wrapped(params, x):
        stage_spec = layout.spec(axis)
        return layout.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: stage_spec, params),
                      layout.spec()),
            out_specs=layout.spec(),
        )(params, x)

    return jax.jit(wrapped)


def stage_shardings(mesh: Mesh, params: Any, axis: str = AXIS_PP) -> Any:
    """NamedShardings putting each leaf's leading (stage) axis on ``axis``."""
    stage = layout.named(mesh, axis)
    return jax.tree.map(lambda _: stage, params)
