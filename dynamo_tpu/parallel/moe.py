"""Mixture-of-Experts FFN with expert parallelism.

WideEP-class capability (ref: the reference's pass-through EP flags,
components/backends/sglang/docs/dsr1-wideep-h100.md — engine-internal there,
first-class here). GShard-style capacity-based dispatch, built entirely from
one-hot matmuls and batched einsums so everything lands on the MXU and the
GSPMD partitioner shards it over the expert mesh axis with automatic
all-to-alls — no per-token gather/scatter, no dynamic shapes.

Sharding contract: expert-stacked weights ``[E, D, F]`` carry
``P("ep"|"tp", None, None)``; the dispatch/combine einsums contract over the
token axis, so XLA materialises per-expert buffers ``[E, C, D]`` sharded over
E — each device computes only its experts.
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert token capacity (static at trace time)."""
    return max(1, math.ceil(num_tokens * top_k / num_experts
                            * capacity_factor))


def moe_ffn(
    x: jax.Array,          # [N, D] tokens (flattened batch)
    w_router: jax.Array,   # [D, E]
    w_gate: jax.Array,     # [E, D, F]
    w_up: jax.Array,       # [E, D, F]
    w_down: jax.Array,     # [E, F, D]
    *,
    top_k: int,
    capacity_factor: float = 2.0,
) -> jax.Array:
    """Top-k routed SwiGLU experts; returns [N, D].

    Tokens overflowing an expert's capacity lose that expert's contribution
    (their combine weight is zeroed and the rest renormalised) — standard
    GShard semantics; raise ``capacity_factor`` for exactness.
    """
    N, D = x.shape
    E = w_router.shape[1]
    C = moe_capacity(N, E, top_k, capacity_factor)
    dt = x.dtype

    logits = (x.astype(jnp.float32) @ w_router.astype(jnp.float32))  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, top_k)      # [N, k]
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)

    # one-hot expert assignment per (token, slot): [N, k, E]
    assign = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
    # position of each (token, slot) within its expert's capacity buffer:
    # running count of prior assignments to the same expert, flattened over
    # (token-major, slot-minor) order
    flat = assign.reshape(N * top_k, E)
    pos = jnp.cumsum(flat, axis=0) - flat                # [N*k, E]
    pos = jnp.sum(pos * flat, axis=-1).reshape(N, top_k)  # [N, k]
    in_cap = pos < C
    gates = jnp.where(in_cap, top_vals, 0.0)             # [N, k]

    # dispatch tensor [N, E, C]: token n -> (expert, capacity slot)
    pos_hot = jax.nn.one_hot(
        jnp.where(in_cap, pos, C), C, dtype=jnp.float32
    )                                                     # [N, k, C]
    dispatch = jnp.einsum("nke,nkc->nec", assign, pos_hot)
    combine = jnp.einsum("nke,nkc,nk->nec", assign, pos_hot, gates)

    xin = jnp.einsum("nec,nd->ecd", dispatch, x.astype(jnp.float32))
    xin = xin.astype(dt)                                  # [E, C, D]
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", xin, w_gate).astype(jnp.float32)
    )
    up = jnp.einsum("ecd,edf->ecf", xin, w_up).astype(jnp.float32)
    h = (gate * up).astype(dt)
    out = jnp.einsum("ecf,efd->ecd", h, w_down)           # [E, C, D]
    return jnp.einsum(
        "nec,ecd->nd", combine, out.astype(jnp.float32)
    ).astype(dt)
