"""Model-parallelism building blocks: SP/CP ring attention, Ulysses
all-to-all attention, expert parallelism, pipeline parallelism.

The reference delegates intra-model parallelism to its engines (SURVEY §2.3:
TP/PP/EP via vLLM/SGLang flags; SP/CP absent upstream) — here the engine is
ours, so these are first-class TPU-native implementations over
``jax.sharding.Mesh`` axes.
"""

from .ring_attention import make_ring_attention, ring_attention
from .ulysses import make_ulysses_attention

__all__ = [
    "ring_attention",
    "make_ring_attention",
    "make_ulysses_attention",
]
