"""Canonical mesh layout — the ONE place axis names and meshes come from.

Every sharded tensor in the system agrees on this vocabulary (SNIPPETS.md
[3]: a ``SpecLayout``-style single source of truth); MULTICHIP_r05's
involuntary-rematerialization storm came from modules free-handing their
own axis strings and mesh shapes.  dynalint rule DT501/DT502 enforces that
axis-name literals and ``Mesh`` construction live here and nowhere else —
new layouts are added by extending this module, not by spelling ``"tp"``
at a call site.

Axes:

- ``dp``   data parallel — independent batch shards
- ``tp``   tensor parallel — attention/MLP heads split per chip
- ``sp``   sequence parallel — ring/Ulysses attention over long prompts
- ``ep``   expert parallel — MoE experts spread over chips
- ``pp``   pipeline parallel — layer stages
- ``fsdp`` fully-sharded data parallel (ROADMAP item 2's 2D/3D target)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"
AXIS_FSDP = "fsdp"

#: every axis name the serving system may use; dynalint's DT501 vocabulary
#: mirrors this tuple (plus the legacy "data" alias it also polices).
ALL_AXES: Tuple[str, ...] = (
    AXIS_DP, AXIS_TP, AXIS_SP, AXIS_EP, AXIS_PP, AXIS_FSDP,
)


def make_mesh(shape: Tuple[int, int], devices=None) -> Mesh:
    """The serving engine's canonical ``(dp, tp)`` mesh.

    Takes the first ``dp*tp`` devices in enumeration order so every host
    in a multihost slice derives the identical mesh.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    dp, tp = shape
    return Mesh(devices[: dp * tp].reshape(dp, tp), (AXIS_DP, AXIS_TP))


def make_flat_mesh(devices, axis_name: str = AXIS_SP) -> Mesh:
    """View a device set as one flat ring (sequence-parallel prefill)."""
    return Mesh(np.asarray(devices).flatten(), (axis_name,))


def make_axes_mesh(shape: Sequence[int], axis_names: Sequence[str],
                   devices=None) -> Mesh:
    """General N-D mesh over the leading ``prod(shape)`` devices; axis
    names must come from :data:`ALL_AXES`."""
    unknown = [a for a in axis_names if a not in ALL_AXES]
    if unknown:
        raise ValueError(
            f"unknown mesh axis names {unknown}; canonical axes: {ALL_AXES}")
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    return Mesh(devices.flatten()[:n].reshape(tuple(shape)),
                tuple(axis_names))
