"""Canonical sharding layout — the ONE place axis names, meshes, and
PartitionSpecs come from.

Every sharded tensor in the system agrees on this vocabulary (SNIPPETS.md
[3]: a ``SpecLayout``-style single source of truth); MULTICHIP_r05's
involuntary-rematerialization storm came from modules free-handing their
own axis strings, mesh shapes, and per-call-site ``PartitionSpec``
literals.  dynalint rules DT501/DT502/DT503 enforce that axis-name
literals, ``Mesh`` construction, and axis-carrying ``PartitionSpec``
construction live here and nowhere else — new layouts are added by
extending this module, not by spelling ``P(None, "tp")`` at a call site.

Axes:

- ``dp``   data parallel — independent batch shards
- ``tp``   tensor parallel — attention/MLP heads split per chip
- ``sp``   sequence parallel — ring/Ulysses attention over long prompts
- ``ep``   expert parallel — MoE experts spread over chips
- ``pp``   pipeline parallel — layer stages
- ``fsdp`` fully-sharded data parallel (parameter storage sharding)

The serving engine's meshes are ``(dp, tp)`` (2D) or ``(dp, fsdp, tp)``
(3D).  Sequence-parallel ring prefill runs over the SAME serving mesh with
the sequence axis sharded over the composite ``(dp, tp)`` (optionally
``(dp, fsdp, tp)``) axes — NOT over a separate flat ``sp`` mesh.  Two
meshes over one device set is exactly what produced the
``{devices=[8,1,1]} -> {devices=[1,4,1,2]}`` reshape storms: GSPMD cannot
translate shardings between meshes and falls back to full
rematerialization on every tensor crossing the boundary.  One mesh, one
spec table, zero involuntary remats.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"
AXIS_FSDP = "fsdp"

#: every axis name the serving system may use; dynalint's DT501 vocabulary
#: mirrors this tuple (plus the legacy "data" alias it also polices).
ALL_AXES: Tuple[str, ...] = (
    AXIS_DP, AXIS_TP, AXIS_SP, AXIS_EP, AXIS_PP, AXIS_FSDP,
)

#: one PartitionSpec entry: None (replicated), an axis name, or a tuple of
#: axis names (composite sharding — e.g. the sequence axis over (dp, tp)).
SpecEntry = Union[None, str, Tuple[str, ...]]


def spec(*entries: SpecEntry) -> PartitionSpec:
    """The one validated ``PartitionSpec`` constructor.

    Entries must be ``None``, a canonical axis name, or a tuple of
    canonical axis names.  Everything outside this module builds its specs
    through here (or the :class:`SpecLayout` methods below) — dynalint
    DT503 flags direct axis-carrying ``PartitionSpec(...)`` calls.
    """
    for e in entries:
        names = e if isinstance(e, tuple) else (e,)
        for a in names:
            if a is not None and a not in ALL_AXES:
                raise ValueError(
                    f"unknown mesh axis {a!r} in spec entry {e!r}; "
                    f"canonical axes: {ALL_AXES}")
    return PartitionSpec(*entries)


def named(mesh: Mesh, *entries: SpecEntry) -> NamedSharding:
    """``NamedSharding(mesh, spec(*entries))`` — validated."""
    return NamedSharding(mesh, spec(*entries))


def replicated(mesh: Mesh) -> NamedSharding:
    """The fully-replicated sharding on ``mesh`` (control state, scalars,
    sampled token ids — everything small enough to live everywhere)."""
    return NamedSharding(mesh, spec())


# --------------------------- version compat -------------------------------
#
# jax moved shard_map from jax.experimental to the top level (renaming the
# replication-check kwarg check_rep -> check_vma) and added lax.axis_size
# along the way.  The serving code targets both: every shard_map in the
# tree goes through this wrapper, and per-shard bodies take the ring size
# from axis_size() below.


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable ``shard_map`` with replication checking disabled
    (our bodies return pallas_call / collective outputs that carry no
    replication info either way)."""
    if hasattr(jax, "shard_map"):  # jax >= 0.6
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def axis_size(axis_name: Union[str, Tuple[str, ...]]) -> int:
    """Size of a (possibly composite) mesh axis inside a shard_map body.

    ``psum(1, axis)`` is constant-folded at trace time, so the result is a
    static python int usable as a loop bound (``lax.axis_size`` does not
    exist on older jax)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


# ------------------------------ meshes ------------------------------------


def make_mesh(shape: Sequence[int], devices=None) -> Mesh:
    """The serving engine's canonical mesh: ``(dp, tp)`` for a 2-tuple,
    ``(dp, fsdp, tp)`` for a 3-tuple.

    Takes the first ``prod(shape)`` devices in enumeration order so every
    host in a multihost slice derives the identical mesh.
    """
    shape = tuple(int(s) for s in shape)
    if len(shape) == 2:
        axes: Tuple[str, ...] = (AXIS_DP, AXIS_TP)
    elif len(shape) == 3:
        axes = (AXIS_DP, AXIS_FSDP, AXIS_TP)
    else:
        raise ValueError(
            f"mesh shape must be (dp, tp) or (dp, fsdp, tp), got {shape}")
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    return Mesh(devices.flatten()[:n].reshape(shape), axes)


def make_flat_mesh(devices, axis_name: str = AXIS_SP) -> Mesh:
    """View a device set as one flat ring.

    NOTE: a flat mesh over devices that already carry a serving mesh is a
    cross-mesh boundary GSPMD pays for with involuntary rematerialization;
    serving-path sequence parallelism shards over the serving mesh's own
    composite axes (:meth:`SpecLayout.seq_axes`) instead.  This stays for
    standalone single-purpose rings (tests, research harnesses).
    """
    return Mesh(np.asarray(devices).flatten(), (axis_name,))


def make_axes_mesh(shape: Sequence[int], axis_names: Sequence[str],
                   devices=None) -> Mesh:
    """General N-D mesh over the leading ``prod(shape)`` devices; axis
    names must come from :data:`ALL_AXES`."""
    unknown = [a for a in axis_names if a not in ALL_AXES]
    if unknown:
        raise ValueError(
            f"unknown mesh axis names {unknown}; canonical axes: {ALL_AXES}")
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    return Mesh(devices.flatten()[:n].reshape(tuple(shape)),
                tuple(axis_names))


# ----------------------------- SpecLayout ---------------------------------


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Frozen per-parameter PartitionSpec table over the serving mesh.

    Each field holds the mesh axis a role shards over, or ``None`` when the
    mesh doesn't carry that axis (or carries it at size 1 — sharding over a
    singleton axis is replication wearing a costume, and naming absent axes
    in a NamedSharding is an error).  Build with :meth:`for_mesh` so the
    table always matches the mesh it will be used with.

    The table (stacked scan tree, ``L`` = layers):

    ====================  ====================  =============================
    leaf                  shape                 spec
    ====================  ====================  =============================
    embed                 [V, D]                (tp, fsdp)   vocab-sharded
    layers/attn_norm      [L, D]                ()           replicated
    layers/wq             [L, D, H*hd]          (None, fsdp, tp)   column
    layers/wk, wv         [L, D, KV*hd]         (None, fsdp, tp)   column
    layers/wo             [L, H*hd, D]          (None, tp, fsdp)   row
    layers/mlp_norm       [L, D]                ()           replicated
    layers/w_gate, w_up   [L, D, F]             (None, fsdp, tp)   column
    layers/w_down         [L, F, D]             (None, tp, fsdp)   row
    layers/w_router       [L, D, E]             ()           replicated
    layers/w_gate (moe)   [L, E, D, F]          (None, ep, None, None)
    layers/w_up (moe)     [L, E, D, F]          (None, ep, None, None)
    layers/w_down (moe)   [L, E, F, D]          (None, ep, None, None)
    final_norm            [D]                   ()           replicated
    lm_head               [D, V]                (fsdp, tp)   column
    KV cache (per layer)  [NB, KV, bs, hd]      (None, tp, None, None)
    KV block transfer     [L, N, KV, bs, hd]    (None, None, tp, None, None)
    hidden states         [B, T, D]             ()    (seq path: (None, seq))
    logits                [B, V]                (None, tp)
    ====================  ====================  =============================

    Column-sharded projections contract over the replicated D axis — each
    output element is computed whole on one chip, so sharded and unsharded
    runs are bitwise identical per partial product; row-sharded projections
    meet the column outputs so the only cross-chip reduction is the one
    Megatron all-reduce per block.  The MoE expert axis rides ``ep`` when
    the mesh has one and falls back to ``tp`` (dispatch/combine become
    all-to-alls under GSPMD).
    """

    dp: Optional[str] = None
    fsdp: Optional[str] = None
    tp: Optional[str] = None
    ep: Optional[str] = None

    @staticmethod
    def for_mesh(mesh: Optional[Mesh]) -> "SpecLayout":
        """Derive the layout from a mesh, dropping absent/singleton axes."""
        if mesh is None:
            return SpecLayout()

        def have(axis: str) -> Optional[str]:
            return axis if mesh.shape.get(axis, 1) > 1 else None

        return SpecLayout(
            dp=have(AXIS_DP),
            fsdp=have(AXIS_FSDP),
            tp=have(AXIS_TP),
            ep=have(AXIS_EP) or have(AXIS_TP),
        )

    # ------------------------- sequence axis ---------------------------

    def seq_axes(self) -> SpecEntry:
        """The composite axis the ring-sp prefill shards the sequence over:
        every data-carrying serving axis — ``("dp", "tp")`` on the 2D mesh.
        Using the serving mesh's own axes (not a separate flat ``sp`` mesh
        over the same devices) is what lets GSPMD reshard ring-layout K/V
        into the head-sharded paged cache without involuntary
        rematerialization.  The order is mesh-major (dp outermost): the
        row-major composite device enumeration then equals the flat device
        enumeration, which is the convention ``axis_index``/``ppermute``
        and shard_map chunk placement all agree on — the ring's chunk->
        owner bookkeeping depends on that agreement.  The seq->heads
        handoff at the cache scatter does not constrain the order; the
        forward pass pins it as an explicit replicate-then-slice, which is
        order-independent."""
        axes = tuple(a for a in (self.dp, self.fsdp, self.tp) if a)
        if not axes:
            return None
        return axes if len(axes) > 1 else axes[0]

    # ----------------------- parameter specs ---------------------------

    def embed(self) -> PartitionSpec:
        return spec(self.tp, self.fsdp)

    def norm_stacked(self) -> PartitionSpec:
        return spec(None, None)

    def norm(self) -> PartitionSpec:
        return spec(None)

    def column_stacked(self) -> PartitionSpec:
        """[L, in, out] column-parallel: wq/wk/wv, dense w_gate/w_up."""
        return spec(None, self.fsdp, self.tp)

    def row_stacked(self) -> PartitionSpec:
        """[L, in, out] row-parallel: wo, dense w_down."""
        return spec(None, self.tp, self.fsdp)

    def router_stacked(self) -> PartitionSpec:
        return spec(None, None, None)

    def expert_stacked(self) -> PartitionSpec:
        """[L, E, in, out] — experts spread over ep (tp fallback)."""
        return spec(None, self.ep, None, None)

    def lm_head(self) -> PartitionSpec:
        return spec(self.fsdp, self.tp)

    @staticmethod
    def scale_spec(weight_spec: PartitionSpec) -> PartitionSpec:
        """Per-channel quantization scales ride the weight's shape with the
        contraction axis (-2) reduced to size 1 (``keepdims``), so the
        scale's spec is the weight's with that entry replicated — sharding
        a singleton dim over a real axis is indivisible."""
        entries = list(weight_spec)
        entries[-2] = None
        return spec(*entries)

    def param_specs(self, cfg, weight_dtype: str = "bf16"
                    ) -> Dict[str, Any]:
        """PartitionSpec tree matching ``model.init_params(cfg)``; with a
        quantized ``weight_dtype`` each matmul leaf becomes a
        ``{"q": weight_spec, "s": scale_spec}`` dict mirroring the
        quantized param pytree (engine/quant.py)."""
        from ..engine import quant

        def w(s: PartitionSpec, name: str) -> Any:
            if quant.is_quantized(weight_dtype) and quant.is_weight_leaf(
                    name):
                return {"q": s, "s": self.scale_spec(s)}
            return s

        layers: Dict[str, Any] = {
            "attn_norm": self.norm_stacked(),
            "wq": w(self.column_stacked(), "wq"),
            "wk": w(self.column_stacked(), "wk"),
            "wv": w(self.column_stacked(), "wv"),
            "wo": w(self.row_stacked(), "wo"),
            "mlp_norm": self.norm_stacked(),
        }
        if cfg.is_moe:
            layers["w_router"] = self.router_stacked()
            layers["w_gate"] = w(self.expert_stacked(), "w_gate")
            layers["w_up"] = w(self.expert_stacked(), "w_up")
            layers["w_down"] = w(self.expert_stacked(), "w_down")
        else:
            layers["w_gate"] = w(self.column_stacked(), "w_gate")
            layers["w_up"] = w(self.column_stacked(), "w_up")
            layers["w_down"] = w(self.row_stacked(), "w_down")
        specs: Dict[str, Any] = {
            "embed": self.embed(),
            "layers": layers,
            "final_norm": self.norm(),
        }
        if not cfg.tie_word_embeddings:
            specs["lm_head"] = w(self.lm_head(), "lm_head")
        return specs

    def param_shardings(self, mesh: Mesh, cfg,
                        weight_dtype: str = "bf16") -> Dict[str, Any]:
        return jax.tree.map(
            functools.partial(NamedSharding, mesh),
            self.param_specs(cfg, weight_dtype),
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    # ---------------------- cache / activations ------------------------

    def cache_block(self) -> PartitionSpec:
        """One paged-cache layer [NB, KV, bs, hd]: KV heads over tp, so
        each chip holds exactly the heads it computes."""
        return spec(None, self.tp, None, None)

    def cache_scale_block(self) -> PartitionSpec:
        """Per-layer KV-scale cache [NB, KV, bs] (quantized kv_dtype):
        heads over tp, matching :meth:`cache_block` minus the hd axis."""
        return spec(None, self.tp, None)

    def cache_specs(self, cfg, kv_dtype: str = "bf16") -> Dict[str, Any]:
        from ..engine import quant

        specs = {
            "k": [self.cache_block()] * cfg.num_layers,
            "v": [self.cache_block()] * cfg.num_layers,
        }
        if quant.is_quantized(kv_dtype):
            specs["ks"] = [self.cache_scale_block()] * cfg.num_layers
            specs["vs"] = [self.cache_scale_block()] * cfg.num_layers
        return specs

    def cache_shardings(self, mesh: Mesh, cfg,
                        kv_dtype: str = "bf16") -> Dict[str, Any]:
        from ..engine import quant

        s = NamedSharding(mesh, self.cache_block())
        out = {"k": [s] * cfg.num_layers, "v": [s] * cfg.num_layers}
        if quant.is_quantized(kv_dtype):
            ss = NamedSharding(mesh, self.cache_scale_block())
            out["ks"] = [ss] * cfg.num_layers
            out["vs"] = [ss] * cfg.num_layers
        return out

    def kv_blocks(self) -> PartitionSpec:
        """Extracted/injected KV block payload [L, N, KV, bs, hd] — the
        disagg transfer layout; KV heads carry tp exactly like the cache,
        so a P->D handoff between equal-TP meshes never reshards."""
        return spec(None, None, self.tp, None, None)

    def kv_scale_blocks(self) -> PartitionSpec:
        """Scale payload [L, N, KV, bs] riding the block transfer when the
        cache is quantized; tp on the heads like :meth:`kv_blocks`."""
        return spec(None, None, self.tp, None)

    def hidden(self) -> PartitionSpec:
        """Dense-path activations [B, T, D]: replicated (the Megatron
        pattern — column/row sharded weights keep per-chip activations
        whole; only heads are ever sharded mid-block)."""
        return spec(None, None, None)

    def hidden_seq(self) -> PartitionSpec:
        """Ring-prefill activations [B, T, D]: T over the composite
        sequence axis."""
        return spec(None, self.seq_axes(), None)

    def heads_seq(self) -> PartitionSpec:
        """Ring-prefill q/k/v [B, T, Hx, hd]: T over the sequence axis."""
        return spec(None, self.seq_axes(), None, None)

    def logits(self) -> PartitionSpec:
        """[B, V] — vocab over tp, matching the column-sharded lm_head."""
        return spec(None, self.tp)


def kv_blocks_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a KV block-transfer payload landing on ``mesh``."""
    return NamedSharding(mesh, SpecLayout.for_mesh(mesh).kv_blocks())


def kv_payload_shardings(mesh: Mesh, keys) -> Dict[str, NamedSharding]:
    """Per-key shardings for a KV block-transfer payload dict: ``k``/``v``
    pages get :meth:`SpecLayout.kv_blocks`, ``ks``/``vs`` scale planes get
    :meth:`SpecLayout.kv_scale_blocks`."""
    lay = SpecLayout.for_mesh(mesh)
    page = NamedSharding(mesh, lay.kv_blocks())
    scale = NamedSharding(mesh, lay.kv_scale_blocks())
    return {k: (scale if k in ("ks", "vs") else page) for k in keys}
