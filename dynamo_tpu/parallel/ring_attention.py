"""Ring attention: sequence/context parallelism over an ICI mesh axis.

Long-context capability the reference lacks natively (SURVEY §2.3 marks
SP/CP/ring "absent" upstream — its lever is conditional disagg + chunked
prefill). Here the sequence is sharded over the ``sp`` mesh axis; each device
computes blockwise attention for its query chunk while K/V chunks rotate
around the ring via ``jax.lax.ppermute``, one hop per step, so:

- memory per device is O(T/sp) — T can exceed single-chip HBM;
- every hop is neighbor-to-neighbor over ICI (no all-gather of the sequence);
- compute overlaps communication: XLA schedules the next chunk's ppermute
  against the current chunk's attention FLOPs.

Softmax is accumulated online (flash-attention style m/l/acc in f32), so the
result is exact — identical to full attention over the unsharded sequence.

Layout contract: global ``q, k, v: [B, T, H, hd]`` sharded ``P(None, "sp")``
on the T axis; output identical. Causal masking uses absolute positions
derived from each chunk's ring position.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import layout


def _block_attend(q, k, v, qpos, kpos, m, l, acc, scale, causal):
    """One blockwise attention accumulation step (all f32).

    q: [B, Tq, H, hd]   k/v: [B, Tk, KV, hd]   qpos: [Tq]   kpos: [Tk]
    m, l: [B, Tq, H, 1]  acc: [B, Tq, H, hd]
    """
    H = q.shape[2]
    KV = k.shape[2]
    G = H // KV
    B, Tq = q.shape[0], q.shape[1]
    Tk = k.shape[1]
    hd = q.shape[3]

    qf = q.reshape(B, Tq, KV, G, hd)
    s = jnp.einsum("btkgh,bskh->btkgs", qf, k) * scale  # [B,Tq,KV,G,Tk]
    if causal:
        mask = kpos[None, :] <= qpos[:, None]           # [Tq, Tk]
        s = jnp.where(mask[None, :, None, None, :], s, -jnp.inf)
    s = s.reshape(B, Tq, H, Tk)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    p = jnp.exp(s - m_safe)                             # [B,Tq,H,Tk]
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum(
        "btkgs,bskh->btkgh", p.reshape(B, Tq, KV, G, Tk), v
    ).reshape(B, Tq, H, hd)
    acc_new = acc * alpha + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,      # [B, C, H, hd] local query chunk (C = T / sp)
    k: jax.Array,      # [B, C, KV, hd] local key chunk
    v: jax.Array,      # [B, C, KV, hd]
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Per-shard ring attention body — call inside ``shard_map``.

    Device i starts holding chunk i (positions [i*C, (i+1)*C)). At step s it
    attends over the chunk that started on device ``(i - s) mod n`` while
    sending its current chunk to neighbor ``i+1``.
    """
    n = layout.axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    B, C, H, hd = q.shape
    scale = 1.0 / np.sqrt(hd)

    qf = q.astype(jnp.float32)
    qpos = i * C + jnp.arange(C)
    m = jnp.full((B, C, H, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, C, H, 1), jnp.float32)
    acc = jnp.zeros((B, C, H, hd), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]
    cur_k, cur_v = k.astype(jnp.float32), v.astype(jnp.float32)
    for s in range(n):
        owner = (i - s) % n              # whose chunk we hold this step
        kpos = owner * C + jnp.arange(C)
        m, l, acc = _block_attend(
            qf, cur_k, cur_v, qpos, kpos, m, l, acc, scale, causal
        )
        if s != n - 1:  # final chunk needs no forwarding
            cur_k = jax.lax.ppermute(cur_k, axis_name, perm)
            cur_v = jax.lax.ppermute(cur_v, axis_name, perm)

    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = True
):
    """Jittable global-array ring attention: ``f(q, k, v) -> out`` with
    q/k/v ``[B, T, H|KV, hd]`` sharded over ``axis`` on T."""
    fn = functools.partial(ring_attention, axis_name=axis, causal=causal)
    spec = layout.spec(None, axis, None, None)
    return jax.jit(layout.shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    ))
