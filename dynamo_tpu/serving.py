"""Shared engine-serving wiring used by the JAX worker and the mocker
(ref: the common shape of components/backends/*/src/dynamo/*/main.py —
create runtime, serve generate + clear_kv_blocks, attach publishers,
register the model, drain on signal)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from .engine.config import EngineConfig
from .engine.engine import EngineCore
from .llm.discovery import ModelDeploymentCard, register_llm
from .llm.tokenizer import Tokenizer
from .runtime.component import DistributedRuntime
from .runtime.signals import install_shutdown_signals
from .runtime.tasks import spawn_logged
from .utils.config import RuntimeConfig
from .utils.logging import get_logger

log = get_logger("serving")


@dataclass
class ServeOptions:
    name: str
    component: str = "backend"
    endpoint: str = "generate"
    advertise_host: str = "127.0.0.1"
    migration_limit: int = 3
    tool_call_parser: Optional[str] = None
    reasoning_parser: Optional[str] = None
    # multimodal EPD: advertisement for the card's runtime_config
    # ({tokens_per_image, image_size, component, endpoint}) and an
    # optional colocated encode handler to serve
    mm: Optional[dict] = None
    mm_handler: object = None


async def serve_engine(
    runtime: DistributedRuntime,
    engine: EngineCore,
    eng_cfg: EngineConfig,
    opts: ServeOptions,
    tokenizer: Optional[Tokenizer] = None,
    handler=None,
):
    """Serve ``engine`` (or a wrapping ``handler``) on the cluster; returns
    the served endpoint and the publishers (caller owns shutdown ordering)."""
    from .router.publisher import KvEventPublisher, WorkerMetricsPublisher

    await engine.start()
    endpoint = (runtime.namespace().component(opts.component)
                .endpoint(opts.endpoint))
    served = await endpoint.serve_endpoint(
        handler if handler is not None else engine,
        advertise_host=opts.advertise_host,
        metadata={"model": opts.name},
    )

    # KV events + load metrics for the KV-aware router / aggregator
    # (ref: publisher.rs; the in-process seam replaces the ZMQ relay)
    kv_pub = KvEventPublisher(endpoint.component, runtime.primary_lease)
    kv_pub.start()
    engine.kv_event_sink = kv_pub.sink
    st = getattr(engine, "spec_stats", None)
    # flight recorder: worker-local engine_* gauges on /metrics, and the
    # same snapshot rides the load-metrics wire ("obs" key) so the
    # aggregator gets per-worker MFU/goodput/waste for planner signals
    obs_fn = None
    if getattr(engine, "obs", None) is not None:
        from .observability.gauges import EngineObsGauges

        obs_gauges = EngineObsGauges(runtime.metrics, engine)
        obs_fn = obs_gauges.refresh
    kvbm = getattr(engine, "kvbm", None)
    prefix = getattr(engine, "prefix", None)
    # prefix counters ride the "kvbm" key of the load-metrics wire; an
    # index-only prefix cache (no KVBM attached) still publishes them
    if kvbm is not None:
        kvbm_fn = kvbm.snapshot
    elif prefix is not None:
        kvbm_fn = prefix.snapshot
    else:
        kvbm_fn = None

    def _faults_fired() -> dict:
        # installed via /debug/faults (chaos replay) or in-process tests;
        # empty when no plan is active so the snapshot stays lean
        from .runtime import faults

        plan = faults.current()
        return plan.fired_counts() if plan is not None else {}

    metrics_pub = WorkerMetricsPublisher(
        endpoint.component, runtime.primary_lease, lambda: engine.stats,
        spec_fn=st.to_dict if st is not None else None,
        obs_fn=obs_fn,
        kvbm_fn=kvbm_fn,
        faults_fn=_faults_fired,
    )
    metrics_pub.start()

    async def clear_kv(request, context):
        engine.clear_kv_blocks()
        yield {"cleared": True}

    clear_ep = (runtime.namespace().component(opts.component)
                .endpoint("clear_kv_blocks"))
    await clear_ep.serve_endpoint(
        clear_kv, advertise_host=opts.advertise_host
    )

    # encode-only embeddings endpoint (device engines only — the mocker has
    # no hidden states; ref: the embeddings route openai.rs:714)
    supports_embeddings = hasattr(engine, "embed_endpoint")
    if supports_embeddings:
        embed_ep = (runtime.namespace().component(opts.component)
                    .endpoint("embed"))
        await embed_ep.serve_endpoint(
            engine.embed_endpoint, advertise_host=opts.advertise_host
        )

    # active canary probes through the real generate path
    # (ref: health_check.rs:44; enabled by DYNTPU_HEALTH_CHECK_ENABLED)
    if runtime.config.health_check_enabled:
        from .runtime.health import (
            HealthCheckConfig, HealthCheckManager, engine_canary,
        )

        def _withdraw(name: str) -> None:
            log.warning("health probe %s unhealthy — withdrawing instance", name)
            spawn_logged(served.withdraw(), name="health-withdraw")

        def _readvertise(name: str) -> None:
            log.info("health probe %s recovered — re-advertising instance", name)
            spawn_logged(served.readvertise(), name="health-readvertise")

        health = HealthCheckManager(
            HealthCheckConfig(period_s=runtime.config.health_check_period_s),
            on_unhealthy=_withdraw,
            on_recovered=_readvertise,
        )
        target = f"{opts.component}/{opts.endpoint}"
        health.register(target, engine_canary(
            handler if handler is not None else engine
        ))
        health.start()
        served.health_manager = health
        if runtime.system_server is not None:
            runtime.system_server.register_probe(
                target, lambda: health.status(target)
            )

    # the planner's degradation ladder can clamp spec_k /
    # prefill_chunk_tokens cluster-wide; opt-in per worker
    # (DYNTPU_PLANNER_APPLY_DEGRADATION) because mutating a live engine
    # config is a behavior change operators must choose
    if runtime.config.planner_apply_degradation:
        from .planner.degradation import (
            DegradationWatcher, apply_engine_clamps,
        )

        originals: dict = {}

        def _apply(actions: dict) -> None:
            changed = apply_engine_clamps(eng_cfg, actions, originals)
            if changed:
                log.info("degradation orders applied to engine: %s", changed)
            # evict_to_host rung: demote idle G1 prefix blocks to the host
            # pool (prefix.manager) — fires on every order change while
            # the rung holds (each deeper engage/release re-delivers it)
            n_evict = int(actions.get("evict_to_host") or 0)
            px = getattr(engine, "prefix", None)
            if n_evict > 0 and px is not None:
                spawn_logged(px.evict_to_host(n_evict),
                             name="prefix-evict-to-host")

        served.degradation_watcher = DegradationWatcher(
            runtime.store, runtime.namespace().name, _apply
        )
        served.degradation_watcher.start()

    if opts.mm_handler is not None:
        mm_ep = (runtime.namespace().component(opts.component)
                 .endpoint("encode"))
        await mm_ep.serve_endpoint(
            opts.mm_handler, advertise_host=opts.advertise_host
        )

    if tokenizer is not None:
        model_type = ["chat", "completions"]
        if supports_embeddings:
            model_type.append("embeddings")
        runtime_config = {
            "total_kv_blocks": eng_cfg.num_blocks,
            "max_num_seqs": eng_cfg.max_num_seqs,
            "max_num_batched_tokens": eng_cfg.max_num_batched_tokens,
        }
        if opts.mm is not None:
            runtime_config["multimodal"] = opts.mm
        card = ModelDeploymentCard(
            name=opts.name,
            model_type=model_type,
            tokenizer_json=tokenizer.to_json_str(),
            chat_template=tokenizer.chat_template,
            context_length=eng_cfg.max_model_len,
            kv_block_size=eng_cfg.block_size,
            migration_limit=opts.migration_limit,
            eos_token_ids=list(tokenizer.eos_token_ids),
            bos_token_id=tokenizer.bos_token_id,
            runtime_config=runtime_config,
            tool_call_parser=opts.tool_call_parser,
            reasoning_parser=opts.reasoning_parser,
        )
        await register_llm(endpoint, card)

    return served, kv_pub, metrics_pub


async def run_until_shutdown(
    runtime: DistributedRuntime, engine: EngineCore,
    served, kv_pub, metrics_pub,
) -> None:
    """Install the graceful drain triggers (SIGINT/SIGTERM and, when the
    system server is up, ``POST /drain``), the maintenance-notice triggers
    (SIGUSR1 / ``POST /preempt`` → evacuating drain), then block on runtime
    shutdown."""
    import msgpack

    from .planner.connector import planner_events_subject
    from .runtime.preemption import (
        PreemptionCoordinator, install_preemption_signal,
    )

    loop = asyncio.get_running_loop()

    def _graceful():
        log.info("drain requested — deregistering and finishing in-flight "
                 "work (deadline %.1fs)", runtime.config.drain_timeout_s)
        spawn_logged(_shutdown(), name="drain-shutdown")

    async def _shutdown():
        health = getattr(served, "health_manager", None)
        if health is not None:
            await health.stop()
        degradation = getattr(served, "degradation_watcher", None)
        if degradation is not None:
            await degradation.stop()
        await served.drain_and_stop(
            deadline_s=runtime.config.drain_timeout_s
        )
        await kv_pub.stop()
        await metrics_pub.stop()
        await engine.stop()
        await runtime.shutdown()

    # signals and POST /drain share one once-latch: whichever arrives
    # first starts the drain, the rest are no-ops (a REPEAT signal while
    # draining hard-exits — see runtime/signals.py)
    guard = install_shutdown_signals(_graceful, loop=loop, name="worker-drain")
    if runtime.system_server is not None:
        runtime.system_server.register_drain(
            served.endpoint.path, guard.trigger
        )

    # maintenance notices: evacuate in-flight KV (peer / host tier / re-
    # prefill fallback), tell the planner so it scales the replacement
    # proactively, then run the same graceful drain
    subject = planner_events_subject(runtime.namespace().name)

    def _preempt_event(event: dict) -> None:
        spawn_logged(
            runtime.store.publish(
                subject, msgpack.packb(event, use_bin_type=True)
            ),
            name="preempt-event",
        )

    coordinator = PreemptionCoordinator(
        engine,
        worker_key=served.endpoint.path,
        notice_grace_s=runtime.config.preempt_notice_grace_s,
        evac_deadline_s=runtime.config.preempt_evac_deadline_s,
        journal_cap=runtime.config.preempt_journal_cap,
        on_event=_preempt_event,
    )
    served.preemption = coordinator

    async def _notice_then_drain(reason: str) -> None:
        await coordinator.notice(reason)
        guard.trigger()

    def _on_notice(reason: str):
        return lambda: spawn_logged(
            _notice_then_drain(reason), name="preempt-notice"
        )

    try:
        install_preemption_signal(coordinator, loop=loop, then=guard.trigger)
    except (NotImplementedError, RuntimeError):
        pass  # no SIGUSR1 on this platform — HTTP trigger still works
    if runtime.system_server is not None:
        runtime.system_server.register_preempt(
            served.endpoint.path, _on_notice("admin")
        )
    metrics_pub.preempt_fn = lambda: {
        "notices": coordinator.num_notices,
        "evacuated_total": coordinator.num_evacuated,
        "spilled_total": coordinator.num_spilled,
        "fallbacks_total": coordinator.num_fallbacks,
    }

    await runtime.shutdown_event.wait()


def load_tokenizer(path: Optional[str]) -> Optional[Tokenizer]:
    if path is None:
        return None
    import os

    if os.path.isdir(path):
        return Tokenizer.from_pretrained_dir(path)
    return Tokenizer.from_file(path)
