"""Standalone metrics aggregator component
(ref: components/metrics/src/main.rs:36 — scrapes worker load metrics,
subscribes KV events, exposes Prometheus).

    python -m dynamo_tpu.metrics_aggregator --component backend --port 9090

Subscribes to a component's ``load_metrics`` and ``kv_events`` subjects and
re-exposes per-worker ForwardPassMetrics as Prometheus gauges plus KV-event
counters (incl. an aggregate prefix-cache hit rate) on a system server.
"""

from __future__ import annotations

import argparse
import asyncio
import time
from typing import Callable, Dict

import msgpack

from .planner.connector import planner_events_subject
from .router.kv_router import KV_EVENTS_SUBJECT, LOAD_METRICS_SUBJECT
from .runtime.component import DistributedRuntime
from .runtime.signals import install_shutdown_signals
from .runtime.system_server import SystemServer
from .runtime.tasks import spawn_logged
from .utils.config import RuntimeConfig
from .utils.logging import get_logger

log = get_logger("metrics_aggregator")


class MetricsAggregator:
    # a worker that has not published stats for this long is gone (crashed
    # or drained) — its gauges must disappear from the scrape, not freeze
    # at their last values forever
    STALE_AFTER_S = 30.0

    def __init__(
        self,
        runtime: DistributedRuntime,
        component: str,
        *,
        stale_after_s: float = STALE_AFTER_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.runtime = runtime
        self.component = runtime.namespace().component(component)
        self.stale_after_s = stale_after_s
        self._clock = clock  # injectable for deterministic expiry tests
        m = runtime.metrics.child(component=component)
        self._g_usage = m.gauge(
            "worker_kv_usage", "per-worker KV usage", ["worker"]
        )
        self._g_running = m.gauge(
            "worker_requests_running", "running requests", ["worker"]
        )
        self._g_waiting = m.gauge(
            "worker_requests_waiting", "waiting requests", ["worker"]
        )
        self._g_hit_rate = m.gauge(
            "prefix_cache_hit_rate", "aggregate prefix cache hit rate"
        )
        self._g_spec_accept = m.gauge(
            "worker_spec_acceptance_rate",
            "per-worker speculative-draft acceptance rate", ["worker"]
        )
        self._g_spec_rate = m.gauge(
            "spec_acceptance_rate",
            "aggregate speculative-draft acceptance rate"
        )
        # flight-recorder feed ("obs" key of the load-metrics snapshot):
        # per-worker live MFU / goodput / padding waste
        self._g_mfu = m.gauge(
            "worker_mfu", "per-worker live MFU (trailing window)", ["worker"]
        )
        self._g_goodput = m.gauge(
            "worker_goodput_tok_s",
            "per-worker goodput tokens/s (trailing window)", ["worker"]
        )
        self._g_pad_waste = m.gauge(
            "worker_padding_waste_ratio",
            "per-worker fraction of dispatched FLOPs burnt on padding",
            ["worker"]
        )
        # recorder lifetime totals (reset at warmup): the replay scoreboard
        # reconciles client-counted tokens against these
        self._g_goodput_total = m.gauge(
            "worker_goodput_tokens_total",
            "per-worker lifetime goodput tokens since warmup", ["worker"]
        )
        self._g_steps_total = m.gauge(
            "worker_steps_total",
            "per-worker lifetime dispatched device windows since warmup",
            ["worker"]
        )
        # disagg handoff health ("disagg" key of the snapshot): fallbacks,
        # breaker state, transfer retries, orphan reaps
        self._g_dg_fallbacks = m.gauge(
            "disagg_fallback_total",
            "per-worker remote-prefill failures that fell back to local",
            ["worker"]
        )
        self._g_dg_breaker = m.gauge(
            "disagg_breaker_open",
            "1 while the worker's handoff breaker is open "
            "(local-prefill cooldown)", ["worker"]
        )
        self._g_dg_retries = m.gauge(
            "disagg_transfer_retries_total",
            "per-worker KV push retry attempts", ["worker"]
        )
        self._g_dg_orphans = m.gauge(
            "disagg_orphans_reaped_total",
            "per-worker deadline-expired handoff entries reaped", ["worker"]
        )
        # kvbm host-tier health ("kvbm" key of the snapshot): resident
        # bytes and spill pressure of each worker's G2/G3 pools
        self._g_kvbm_bytes = m.gauge(
            "kvbm_host_pool_bytes",
            "per-worker bytes resident in the kvbm host (G2) pool",
            ["worker"]
        )
        self._g_kvbm_spills = m.gauge(
            "kvbm_spills_total",
            "per-worker G2→G3 disk spills", ["worker"]
        )
        self._g_kvbm_onboard_reqs = m.gauge(
            "kvbm_onboard_requests_total",
            "per-worker admissions that onboarded host-tier blocks",
            ["worker"]
        )
        self._g_kvbm_g4_puts = m.gauge(
            "kvbm_g4_puts_total",
            "per-worker write-throughs to the cluster G4 tier", ["worker"]
        )
        self._g_kvbm_g4_hits = m.gauge(
            "kvbm_g4_hits_total",
            "per-worker blocks onboarded from the cluster G4 tier",
            ["worker"]
        )
        self._g_kvbm_peer_hits = m.gauge(
            "kvbm_peer_hits_total",
            "per-worker blocks onboarded from a peer worker's G2 pool",
            ["worker"]
        )
        # global prefix cache (radix index counters ride the same "kvbm"
        # key; zero-defaulted for workers without a prefix cache attached)
        self._g_prefix_nodes = m.gauge(
            "worker_prefix_nodes",
            "per-worker radix prefix index nodes", ["worker"]
        )
        self._g_prefix_hit_tokens = m.gauge(
            "worker_prefix_hit_tokens_total",
            "per-worker prompt tokens served from the prefix cache "
            "(index-verified)", ["worker"]
        )
        self._g_prefix_evictions = m.gauge(
            "worker_prefix_evictions_total",
            "per-worker prefix blocks evicted/demoted out of a tier",
            ["worker"]
        )
        # preemption tolerance ("preempt" key): maintenance notices seen
        # and where the evacuated seats went
        self._g_preempt_notices = m.gauge(
            "worker_preempt_notices",
            "per-worker maintenance notices received", ["worker"]
        )
        self._g_preempt_evacuated = m.gauge(
            "worker_preempt_evacuated_total",
            "per-worker seats evacuated to a peer", ["worker"]
        )
        # chaos visibility ("faults" key): per-worker fault-plan firings so
        # a replay's attribution cross-check can read the live deployment
        self._g_faults_fired = m.gauge(
            "worker_faults_fired_total",
            "per-worker injected-fault firings by site and kind",
            ["worker", "site", "kind"]
        )
        # (site, kind) label sets seen per worker — expire_stale must drop
        # exactly these, and absent sites must re-zero, not freeze
        self._fault_labels: Dict[str, set] = {}
        self._g_wave_recovery = m.gauge(
            "replay_wave_recovery_windows",
            "windows until per-tier SLO compliance returned after a "
            "replayed fault wave (-1 while unrecovered)", ["wave"]
        )
        self._c_events = m.counter(
            "kv_events_total", "KV events seen", ["kind"]
        )
        # planner control-loop visibility: the degradation ladder's current
        # level, the latest scaling targets, and every transition
        self._g_degradation = m.gauge(
            "planner_degradation_level",
            "engaged degradation-ladder steps (0 = none)"
        )
        self._g_targets = m.gauge(
            "planner_target_replicas",
            "latest planner replica target", ["role"]
        )
        self._c_transitions = m.counter(
            "planner_transitions_total",
            "planner control-loop transitions", ["kind", "detail"]
        )
        self.worker_stats: Dict[str, dict] = {}
        self._last_seen: Dict[str, float] = {}
        self._tasks = []

    async def start(self, signals_interval_s: float = 0.0) -> None:
        """Subscribe the metric feeds; ``signals_interval_s`` > 0 also
        publishes the aggregated planner signals (worker queue depth + spec
        acceptance) on ``{ns}/planner_signals`` at that cadence."""
        store = self.runtime.store
        for subject, handler in (
            (self.component.event_subject(LOAD_METRICS_SUBJECT),
             self._on_stats),
            (self.component.event_subject(KV_EVENTS_SUBJECT),
             self._on_kv_event),
            (planner_events_subject(self.component.namespace.name),
             self._on_planner_event),
        ):
            stream = await store.subscribe(subject)
            self._tasks.append(asyncio.create_task(
                self._pump(subject, stream, handler)
            ))
        if signals_interval_s > 0:
            self._tasks.append(asyncio.create_task(
                self._publish_signals(signals_interval_s)
            ))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()

    async def _pump(self, subject: str, stream, handler) -> None:
        while True:
            event = await stream.next()
            if event is None or event["event"] == "dropped":
                log.warning("subscription %s lost — resubscribing", subject)
                await stream.cancel()
                while True:
                    try:
                        stream = await self.runtime.store.subscribe(subject)
                        break
                    except Exception:
                        await asyncio.sleep(0.5)
                continue
            if event["event"] != "msg":
                continue
            try:
                handler(msgpack.unpackb(event["value"], raw=False))
            except Exception:
                log.exception("bad payload on %s", subject)

    def _on_stats(self, snap: dict) -> None:
        wid = str(snap.get("worker_id", "?"))
        self.worker_stats[wid] = snap
        self._last_seen[wid] = self._clock()
        self._g_usage.labels(worker=wid).set(snap.get("kv_usage", 0.0))
        self._g_running.labels(worker=wid).set(
            snap.get("num_requests_running", 0))
        self._g_waiting.labels(worker=wid).set(
            snap.get("num_requests_waiting", 0))
        # forward-compat: pre-spec workers publish no "spec" field — treat
        # it as all-zero stats rather than choking on the absent key
        spec = snap.get("spec") or {}
        drafted = spec.get("drafted", 0)
        self._g_spec_accept.labels(worker=wid).set(
            spec.get("accepted", 0) / drafted if drafted else 0.0)
        # forward-compat: workers without the flight recorder (older build,
        # DYNTPU_OBS_ENABLED=0, the mocker) publish no "obs" — zero-default
        obs = snap.get("obs") or {}
        self._g_mfu.labels(worker=wid).set(obs.get("mfu", 0.0))
        self._g_goodput.labels(worker=wid).set(obs.get("goodput_tok_s", 0.0))
        self._g_pad_waste.labels(worker=wid).set(
            obs.get("padding_waste_ratio", 0.0))
        self._g_goodput_total.labels(worker=wid).set(
            obs.get("total_goodput_tokens", 0.0))
        self._g_steps_total.labels(worker=wid).set(
            obs.get("total_steps", 0.0))
        # forward-compat: non-disagg workers publish no "disagg" — zero
        dg = snap.get("disagg") or {}
        self._g_dg_fallbacks.labels(worker=wid).set(
            dg.get("fallback_total", 0.0))
        self._g_dg_breaker.labels(worker=wid).set(
            dg.get("breaker_open", 0.0))
        self._g_dg_retries.labels(worker=wid).set(
            dg.get("transfer_retries_total", 0.0))
        self._g_dg_orphans.labels(worker=wid).set(
            dg.get("orphans_reaped_total", 0.0))
        # forward-compat: workers without an attached kvbm publish no
        # "kvbm", pre-preemption workers no "preempt" — zero-default both
        kb = snap.get("kvbm") or {}
        self._g_kvbm_bytes.labels(worker=wid).set(
            kb.get("host_pool_bytes", 0.0))
        self._g_kvbm_spills.labels(worker=wid).set(
            kb.get("spills_total", 0.0))
        self._g_kvbm_onboard_reqs.labels(worker=wid).set(
            kb.get("onboard_requests_total", 0.0))
        self._g_kvbm_g4_puts.labels(worker=wid).set(
            kb.get("g4_puts_total", 0.0))
        self._g_kvbm_g4_hits.labels(worker=wid).set(
            kb.get("g4_hits_total", 0.0))
        self._g_kvbm_peer_hits.labels(worker=wid).set(
            kb.get("peer_hits_total", 0.0))
        self._g_prefix_nodes.labels(worker=wid).set(
            kb.get("prefix_nodes", 0.0))
        self._g_prefix_hit_tokens.labels(worker=wid).set(
            kb.get("prefix_hit_tokens_total", 0.0))
        self._g_prefix_evictions.labels(worker=wid).set(
            kb.get("prefix_evictions_total", 0.0))
        pe = snap.get("preempt") or {}
        self._g_preempt_notices.labels(worker=wid).set(
            pe.get("notices", 0.0))
        self._g_preempt_evacuated.labels(worker=wid).set(
            pe.get("evacuated_total", 0.0))
        # forward-compat: workers without an installed fault plan publish
        # no "faults" — zero-default every label set seen so far rather
        # than freezing stale firings after a plan is cleared
        fired = snap.get("faults") or {}
        labels = self._fault_labels.setdefault(wid, set())
        for key, count in fired.items():
            site, _, kind = key.partition("/")
            labels.add((site, kind))
            self._g_faults_fired.labels(
                worker=wid, site=site, kind=kind).set(count)
        for site, kind in labels:
            if f"{site}/{kind}" not in fired:
                self._g_faults_fired.labels(
                    worker=wid, site=site, kind=kind).set(0.0)
        self.expire_stale()
        self._recompute_hit_rate()
        self._recompute_spec_rate()

    def expire_stale(self) -> None:
        """Drop workers whose stats went silent past ``stale_after_s`` and
        clear their per-worker gauge label sets from the registry."""
        now = self._clock()
        stale = [wid for wid, seen in self._last_seen.items()
                 if now - seen > self.stale_after_s]
        for wid in stale:
            self.worker_stats.pop(wid, None)
            self._last_seen.pop(wid, None)
            for gauge in (self._g_usage, self._g_running, self._g_waiting,
                          self._g_spec_accept, self._g_mfu, self._g_goodput,
                          self._g_goodput_total, self._g_steps_total,
                          self._g_pad_waste, self._g_dg_fallbacks,
                          self._g_dg_breaker, self._g_dg_retries,
                          self._g_dg_orphans, self._g_kvbm_bytes,
                          self._g_kvbm_spills, self._g_kvbm_onboard_reqs,
                          self._g_kvbm_g4_puts, self._g_kvbm_g4_hits,
                          self._g_kvbm_peer_hits, self._g_prefix_nodes,
                          self._g_prefix_hit_tokens,
                          self._g_prefix_evictions,
                          self._g_preempt_notices,
                          self._g_preempt_evacuated):
                gauge.remove(worker=wid)
            for site, kind in self._fault_labels.pop(wid, set()):
                self._g_faults_fired.remove(
                    worker=wid, site=site, kind=kind)
            log.info("expired stale worker %s from the scrape", wid)

    def _recompute_hit_rate(self) -> None:
        hits = sum(s.get("prefix_cache_hits", 0)
                   for s in self.worker_stats.values())
        queries = sum(s.get("prefix_cache_queries", 0)
                      for s in self.worker_stats.values())
        self._g_hit_rate.set(hits / queries if queries else 0.0)

    def _recompute_spec_rate(self) -> None:
        drafted = sum((s.get("spec") or {}).get("drafted", 0)
                      for s in self.worker_stats.values())
        accepted = sum((s.get("spec") or {}).get("accepted", 0)
                       for s in self.worker_stats.values())
        self._g_spec_rate.set(accepted / drafted if drafted else 0.0)

    def _on_kv_event(self, payload: dict) -> None:
        kind = payload.get("event", {}).get("kind", "unknown")
        self._c_events.labels(kind=kind).inc()

    # ---------------------- planner control loop ------------------------

    def _on_planner_event(self, event: dict) -> None:
        kind = event.get("kind", "unknown")
        if kind == "degradation":
            self._g_degradation.set(event.get("level", 0))
            self._c_transitions.labels(
                kind="degradation",
                detail=f"{event.get('direction')}:{event.get('step')}",
            ).inc()
        elif kind == "scale":
            for role in ("prefill", "decode"):
                if role in event:
                    self._g_targets.labels(role=role).set(event[role])
            self._c_transitions.labels(kind="scale", detail="targets").inc()
        elif kind == "preemption":
            # a worker announced a maintenance notice (or the planner
            # echoed one): count it so dashboards line the evacuation up
            # against the scale response
            self._c_transitions.labels(
                kind="preemption",
                detail=str(event.get("worker") or event.get("notices")
                           or "notice"),
            ).inc()
        elif kind == "replay_wave":
            # a chaos replay scored one fault wave: publish its recovery
            # verdict so dashboards overlay it on the worker gauges
            # (-1 = the tiers never got back under SLO in this run)
            windows = event.get("windows_to_recover")
            self._g_wave_recovery.labels(
                wave=str(event.get("wave", "?"))
            ).set(-1.0 if windows is None else float(windows))
            self._c_transitions.labels(
                kind="replay_wave", detail=str(event.get("wave", "?"))
            ).inc()

    def queue_depth(self) -> int:
        """Requests waiting across every live worker (the planner's
        backlog signal)."""
        return int(sum(s.get("num_requests_waiting", 0)
                       for s in self.worker_stats.values()))

    def spec_acceptance(self):
        drafted = sum((s.get("spec") or {}).get("drafted", 0)
                      for s in self.worker_stats.values())
        accepted = sum((s.get("spec") or {}).get("accepted", 0)
                       for s in self.worker_stats.values())
        return accepted / drafted if drafted else None

    def preempt_notices(self) -> int:
        """Maintenance notices across live workers (the planner treats a
        noticed worker as capacity already on its way out)."""
        return int(sum((s.get("preempt") or {}).get("notices", 0)
                       for s in self.worker_stats.values()))

    def _obs_mean(self, key: str):
        """Mean of a flight-recorder field over workers that publish it
        (None when nobody does — signals must distinguish 'no recorder'
        from 'recorder says zero')."""
        vals = [(s.get("obs") or {}).get(key)
                for s in self.worker_stats.values()]
        vals = [v for v in vals if v is not None]
        return sum(vals) / len(vals) if vals else None

    def goodput_tok_s(self):
        """Aggregate goodput across live workers (sum, not mean)."""
        vals = [(s.get("obs") or {}).get("goodput_tok_s")
                for s in self.worker_stats.values()]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    def goodput_tokens_total(self):
        """Summed recorder lifetime goodput tokens across live workers
        (None when no worker publishes a recorder) — the live-deployment
        side of the replay token cross-check."""
        vals = [(s.get("obs") or {}).get("total_goodput_tokens")
                for s in self.worker_stats.values()]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    async def _publish_signals(self, interval_s: float) -> None:
        """The aggregator's side of the planner feed: worker-queue backlog
        and aggregate spec acceptance, published like frontend_stats."""
        subject = f"{self.component.namespace.name}/planner_signals"
        while True:
            await asyncio.sleep(interval_s)
            self.expire_stale()
            payload = {
                "queue_depth": self.queue_depth(),
                "spec_acceptance": self.spec_acceptance(),
                "preempt_notices": self.preempt_notices(),
                "num_workers": len(self.worker_stats),
                # flight-recorder aggregates (None with no recorder-bearing
                # workers): fleet-mean utilization/waste + summed goodput
                "mfu": self._obs_mean("mfu"),
                "padding_waste_ratio": self._obs_mean("padding_waste_ratio"),
                "spec_reject_waste_ratio": self._obs_mean(
                    "spec_reject_waste_ratio"),
                "goodput_tok_s": self.goodput_tok_s(),
            }
            try:
                await self.runtime.store.publish(
                    subject, msgpack.packb(payload, use_bin_type=True)
                )
            except Exception as exc:
                log.warning("planner signals publish failed: %s", exc)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu metrics aggregator")
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--component", default="backend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9090)
    p.add_argument(
        "--signals-interval", type=float, default=10.0,
        help="seconds between planner_signals publishes (worker queue "
             "depth + spec acceptance for the SLA planner; 0 disables)",
    )
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(config)

    agg = MetricsAggregator(runtime, args.component)
    await agg.start(signals_interval_s=args.signals_interval)
    server = SystemServer(metrics=runtime.metrics, host=args.host,
                          port=args.port)
    await server.start()

    async def _shutdown():
        await agg.stop()
        await server.stop()
        await runtime.shutdown()

    install_shutdown_signals(
        lambda: spawn_logged(_shutdown(), name="aggregator-shutdown"),
        loop=asyncio.get_running_loop(), name="aggregator",
    )
    log.info("metrics aggregator on %s:%d (component=%s)",
             args.host, server.port, args.component)
    await runtime.shutdown_event.wait()


def main(argv=None) -> None:
    asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    main()
