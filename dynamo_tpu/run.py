"""Single-command launcher: ``in=X out=Y`` like the reference's dynamo-run
(ref: launch/dynamo-run/src/main.rs:31 — ``dynamo-run in=[http|text|batch:…]
out=[auto|mocker|echo|dyn://…]``).

    python -m dynamo_tpu.run in=text out=engine --model tiny
    python -m dynamo_tpu.run in=http out=mocker --port 8000
    python -m dynamo_tpu.run in=batch:prompts.jsonl out=engine --model 1b \
        --weights /models/llama3-1b

Inputs: ``http`` (OpenAI frontend, in-process engine — no cluster needed),
``text`` (interactive REPL), ``batch:FILE`` (JSONL prompts → JSONL results).
Outputs: ``engine`` (JAX engine), ``mocker`` (device-free simulator),
``echo`` (token echo — protocol debugging).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Optional

from .engine.config import EngineConfig, ModelConfig
from .llm.protocols import BackendOutput
from .runtime.context import Context
from .utils.logging import get_logger

log = get_logger("run")

MODEL_PRESETS = {
    "tiny": ModelConfig.tiny,
    "1b": ModelConfig.llama3_1b,
    "8b": ModelConfig.llama3_8b,
    "70b": ModelConfig.llama3_70b,
    "mixtral": ModelConfig.mixtral_8x7b,
}


class EchoEngine:
    """out=echo: stream the prompt's tokens back (ref: Output::Echo)."""

    async def generate(self, request, context):
        delay = 0.01
        toks = list(request.get("token_ids", []))
        for i, t in enumerate(toks):
            await asyncio.sleep(delay)
            yield {"token_ids": [t], "index": i,
                   "finished": i == len(toks) - 1,
                   "finish_reason": "stop" if i == len(toks) - 1 else None,
                   "num_prompt_tokens": len(toks)}

    async def start(self):
        pass

    async def stop(self):
        pass


def build_output(args):
    """Engine for the ``out=`` side."""
    if args.out == "echo":
        return EchoEngine()
    if args.out == "mocker":
        from .mocker.engine import MockEngine

        return MockEngine(EngineConfig(
            num_blocks=args.num_blocks, block_size=args.block_size,
        ))
    # out=engine
    from .engine.engine import InferenceEngine

    model_cfg = MODEL_PRESETS[args.model]()
    dp, tp = (int(x) for x in args.mesh.split(","))
    params = None
    if args.weights:
        from .engine.weights import (
            load_hf_params, load_hf_params_sharded, model_config_from_hf,
        )
        import os

        if os.path.exists(os.path.join(args.weights, "config.json")):
            model_cfg = model_config_from_hf(args.weights)
        if dp * tp > 1:
            # stream each checkpoint shard straight onto device shards —
            # peak host memory stays at one tensor, not the whole model
            import jax

            from .engine import model as model_lib

            mesh = model_lib.make_mesh((dp, tp), jax.devices())
            params = load_hf_params_sharded(args.weights, model_cfg, mesh)
        else:
            params = load_hf_params(args.weights, model_cfg)
    eng_cfg = EngineConfig(
        num_blocks=args.num_blocks, block_size=args.block_size,
        max_model_len=min(args.max_model_len, model_cfg.max_position),
        mesh_shape=(dp, tp),
    )
    return InferenceEngine(model_cfg, eng_cfg, params=params)


def build_tokenizer(args) -> Optional[object]:
    from .serving import load_tokenizer

    path = args.tokenizer or args.weights
    if path is None:
        return None
    try:
        return load_tokenizer(path)
    except Exception:
        log.warning("no tokenizer at %s — running token-id mode", path)
        return None


async def run_text(engine, tokenizer, args) -> None:
    """Interactive REPL (ref: Input::Text)."""
    await engine.start()
    print("dynamo-tpu text mode — empty line exits", file=sys.stderr)
    loop = asyncio.get_running_loop()
    while True:
        line = await loop.run_in_executor(None, _read_prompt)
        if not line:
            break
        if tokenizer is not None:
            token_ids = tokenizer.encode(line)
            stream = tokenizer.stream(token_ids)
        else:
            token_ids = [int(x) for x in line.split()]
            stream = None
        req = {"token_ids": token_ids, "max_tokens": args.max_tokens,
               "temperature": args.temperature}
        async for out in engine.generate(req, Context()):
            for t in out.get("token_ids", []):
                text = stream.push([t]) if stream is not None else f" {t}"
                print(text, end="", flush=True)
        if stream is not None:
            print(stream.flush(), end="")
        print()
    await engine.stop()


def _read_prompt() -> str:
    try:
        return input("> ").strip()
    except EOFError:
        return ""


async def run_batch(engine, tokenizer, args, path: str) -> None:
    """JSONL prompts in → JSONL completions out (ref: Input::Batch)."""
    await engine.start()
    rows = []
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))

    async def one(i, row):
        if "token_ids" in row:
            token_ids = row["token_ids"]
        elif tokenizer is not None:
            token_ids = tokenizer.encode(row.get("prompt", ""))
        else:
            raise ValueError(f"row {i}: no token_ids and no tokenizer")
        req = {"token_ids": token_ids,
               "max_tokens": row.get("max_tokens", args.max_tokens),
               "temperature": row.get("temperature", args.temperature)}
        out_tokens = []
        t0 = time.perf_counter()
        async for out in engine.generate(req, Context()):
            out_tokens.extend(out.get("token_ids", []))
        text = tokenizer.decode(out_tokens) if tokenizer else None
        return {"index": i, "prompt_tokens": len(token_ids),
                "completion_tokens": len(out_tokens),
                "token_ids": out_tokens, "text": text,
                "latency_s": round(time.perf_counter() - t0, 4)}

    results = await asyncio.gather(
        *(one(i, row) for i, row in enumerate(rows))
    )
    for r in results:
        print(json.dumps(r))
    await engine.stop()


async def run_http(engine, tokenizer, args) -> None:
    """OpenAI frontend over an in-process engine — the no-cluster quickstart
    (ref: dynamo-run in=http out=<local engine>)."""
    from .frontend.service import HttpService, ModelEntry, ModelManager
    from .llm.entrypoint import build_local_pipeline

    await engine.start()
    if tokenizer is None:
        raise SystemExit("in=http needs --tokenizer or --weights")
    name = args.model_name or args.model
    pipeline = build_local_pipeline(
        engine, tokenizer, model_name=name,
        max_context_len=args.max_model_len,
    )
    manager = ModelManager()
    manager.register(ModelEntry(name=name, engine=pipeline))
    service = HttpService(manager, host=args.host, port=args.port)
    await service.start()
    log.info("serving %s on %s:%d", name, args.host, service.port)
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop()
        await engine.stop()


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="dynamo-tpu single-command launcher",
        usage="python -m dynamo_tpu.run in=<http|text|batch:FILE> "
              "out=<engine|mocker|echo> [options]",
    )
    p.add_argument("io", nargs=2, metavar="in=/out=",
                   help="in=http|text|batch:FILE and out=engine|mocker|echo")
    p.add_argument("--model", default="tiny", choices=sorted(MODEL_PRESETS))
    p.add_argument("--model-name", default=None)
    p.add_argument("--weights", default=None)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--mesh", default="1,1")
    p.add_argument("--num-blocks", type=int, default=2048)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-model-len", type=int, default=8192)
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    args = p.parse_args(argv)
    spec = {}
    for part in args.io:
        k, _, v = part.partition("=")
        spec[k] = v
    if "in" not in spec or "out" not in spec:
        p.error("both in= and out= are required")
    args.inp, args.out = spec["in"], spec["out"]
    if args.out not in ("engine", "mocker", "echo"):
        p.error(f"unknown out={args.out}")
    return args


def main(argv=None) -> None:
    args = parse_args(argv)
    engine = build_output(args)
    tokenizer = build_tokenizer(args)
    if args.inp == "text":
        asyncio.run(run_text(engine, tokenizer, args))
    elif args.inp.startswith("batch:"):
        asyncio.run(run_batch(engine, tokenizer, args,
                              args.inp.split(":", 1)[1]))
    elif args.inp == "http":
        asyncio.run(run_http(engine, tokenizer, args))
    else:
        raise SystemExit(f"unknown in={args.inp}")


if __name__ == "__main__":
    main()
