"""Per-worker circuit breakers: fail fast instead of retrying into a corpse.

Classic three-state breaker (ref: the failure-isolation layer P/D-Serve and
DynaServe both report as load-bearing at scale — see PAPERS.md):

- **closed** — traffic flows; consecutive transport failures count up.
- **open** — ``failure_threshold`` consecutive failures (or an explicit
  ``trip()`` from a health-check flip) divert all traffic for
  ``open_timeout_s``.
- **half-open** — after the timeout, up to ``half_open_probes`` in-flight
  probe requests are let through; one success closes the breaker, one
  failure re-opens it with a fresh timeout.

The router consults :meth:`CircuitBreaker.allow` when filtering candidate
workers (non-mutating), then calls :meth:`begin` for the worker it actually
selected so half-open probe slots are only consumed by real attempts.
The clock is injectable for deterministic tests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

log = get_logger("circuit")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class BreakerConfig:
    failure_threshold: int = 3    # consecutive failures → open
    open_timeout_s: float = 5.0   # open → half-open probation delay
    half_open_probes: int = 1     # concurrent probes allowed in half-open


class CircuitBreaker:
    def __init__(self, config: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or BreakerConfig()
        self._clock = clock
        self._state = CLOSED
        self._failures = 0           # consecutive, while closed
        self._opened_at = 0.0
        self._probes_inflight = 0
        self.num_trips = 0

    @property
    def state(self) -> str:
        """Current state; resolves open → half-open once the timeout passed."""
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.config.open_timeout_s):
            self._state = HALF_OPEN
            self._probes_inflight = 0
        return self._state

    def allow(self) -> bool:
        """May a request be routed here? Non-mutating (no probe reserved)."""
        state = self.state
        if state == CLOSED:
            return True
        if state == HALF_OPEN:
            return self._probes_inflight < self.config.half_open_probes
        return False

    def begin(self) -> None:
        """An attempt was actually dispatched; reserves a half-open probe."""
        if self.state == HALF_OPEN:
            self._probes_inflight += 1

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            log.info("breaker half-open probe succeeded — closing")
        self._state = CLOSED
        self._failures = 0
        self._probes_inflight = 0

    def record_failure(self) -> None:
        state = self.state
        if state == HALF_OPEN:
            self._trip("half-open probe failed")
            return
        if state == OPEN:
            return
        self._failures += 1
        if self._failures >= self.config.failure_threshold:
            self._trip(f"{self._failures} consecutive failures")

    def trip(self, reason: str = "external") -> None:
        """Force open (health-check flip, manual quarantine)."""
        self._trip(reason)

    def _trip(self, reason: str) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._failures = 0
        self._probes_inflight = 0
        self.num_trips += 1
        log.warning("circuit OPEN (%s) for %.1fs", reason,
                    self.config.open_timeout_s)


class CircuitBreakerRegistry:
    """Breaker per worker id, minted on first touch. Fed by transport error
    codes (router side) and health-check flips (``trip``/``reset``)."""

    def __init__(self, config: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or BreakerConfig()
        self._clock = clock
        self._breakers: Dict[int, CircuitBreaker] = {}

    def breaker(self, worker_id: int) -> CircuitBreaker:
        b = self._breakers.get(worker_id)
        if b is None:
            b = self._breakers[worker_id] = CircuitBreaker(
                self.config, self._clock
            )
        return b

    def allow(self, worker_id: int) -> bool:
        b = self._breakers.get(worker_id)
        return True if b is None else b.allow()

    def begin(self, worker_id: int) -> None:
        self.breaker(worker_id).begin()

    def record_success(self, worker_id: int) -> None:
        b = self._breakers.get(worker_id)
        if b is not None:
            b.record_success()

    def record_failure(self, worker_id: int) -> None:
        self.breaker(worker_id).record_failure()

    def trip(self, worker_id: int, reason: str = "external") -> None:
        self.breaker(worker_id).trip(reason)

    def remove(self, worker_id: int) -> None:
        self._breakers.pop(worker_id, None)

    def states(self) -> Dict[int, str]:
        return {w: b.state for w, b in self._breakers.items()}
