"""Preemption-tolerant serving: maintenance-notice KV evacuation.

TPU slices are reclaimed with notice (maintenance events, spot preemption,
autoscaler scale-downs). A worker that simply dies forfeits every in-flight
seat's KV — each interrupted request pays a full re-prefill somewhere else.
This module turns a notice into an **evacuating drain**:

1. A maintenance notice arrives (``SIGUSR1``, ``POST /preempt`` on the
   system server, or a direct :meth:`PreemptionCoordinator.notice` call).
2. Every decoding seat is journaled (prompt, emitted tokens, sampling
   state, KV progress) in a :class:`SeatJournal` ring — the record alone
   is enough to resume the request byte-identically anywhere, so even a
   botched hand-off degrades to Migration-style recompute, never to a
   dropped request.
3. Seats are parked (``SeqStatus.EVACUATING``: no new windows, blocks
   pinned), quiesced, and their KV is streamed to a peer decode worker
   over the device plane into an epoch-guarded reservation — the receiver
   continues mid-stream from the journaled sampling position. With no
   peer available, sealed blocks spill to the kvbm host pool (and the
   store remote tier when configured) so the re-admitted request's
   prefill is served from cache instead of recomputed.
4. The planner hears about the notice (a ``preemption`` planner event) and
   treats it as a proactive scale signal, compensating capacity before
   the dying worker drops out of the fleet.

Fault seams (``runtime.faults``): ``preempt.notice`` (``drop`` = notice
lost, the kill lands cold) and ``preempt.evacuate`` (``drop`` = a seat's
hand-off fails → journal fallback; ``delay`` = slow evacuation racing the
deadline). The chaos storms in ``mocker.cluster`` drive both.
"""

from __future__ import annotations

import asyncio
import signal as _signal
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from . import faults
from .tasks import spawn_logged

log = get_logger("preemption")

PEER = "peer"            # KV streamed to a peer reservation
SPILL = "spill"          # sealed blocks spilled to the host/remote tier
FALLBACK = "fallback"    # journal-only: resume is a full re-prefill
FINISHED = "finished"    # seat completed naturally while quiescing


@dataclass
class SeatRecord:
    """Everything needed to resume one seat byte-identically elsewhere.

    ``num_computed`` is the KV frontier at quiesce time: tokens before it
    have KV on the source device; ``all_tokens()[num_computed]`` is the
    first token the receiver re-emits. Sampling is keyed on (seed,
    absolute position), so carrying the seed reproduces the tail exactly
    whether the KV moved or the receiver re-prefills from the record.
    """

    seq_id: str
    prompt_ids: List[int]
    output_ids: List[int]
    num_computed: int
    max_tokens: int
    temperature: float
    top_k: int
    top_p: float
    seed: int                       # device-range seed (-1 = unseeded)
    eos_token_ids: Tuple[int, ...]
    generation: int = 0             # times this seat has been evacuated
    spec_drafted: int = 0
    spec_accepted: int = 0

    @classmethod
    def from_seq(cls, seq, generation: int = 0) -> "SeatRecord":
        return cls(
            seq_id=seq.seq_id,
            prompt_ids=list(seq.prompt_ids),
            output_ids=list(seq.output_ids),
            num_computed=seq.num_computed,
            max_tokens=seq.max_tokens,
            temperature=seq.temperature,
            top_k=seq.top_k,
            top_p=seq.top_p,
            seed=seq.seed,
            eos_token_ids=tuple(seq.eos_token_ids),
            generation=generation,
        )

    @property
    def all_tokens(self) -> List[int]:
        return list(self.prompt_ids) + list(self.output_ids)

    def _wire_sampling(self) -> dict:
        return {
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "seed": None if self.seed < 0 else self.seed,
            "eos_token_ids": tuple(self.eos_token_ids),
            "ignore_eos": not self.eos_token_ids,
        }

    def peer_request(self):
        """Request for the receiving worker's epoch-guarded reservation:
        the computed prefix rides as prompt (its KV arrives by transfer),
        the budget covers the re-emitted splice token plus the remainder."""
        from ..engine.engine import Request

        total = len(self.prompt_ids) + len(self.output_ids)
        remaining = self.max_tokens - len(self.output_ids)
        return Request(
            request_id=self.seq_id,
            token_ids=self.all_tokens[: self.num_computed],
            max_tokens=max(1, remaining + (total - self.num_computed)),
            **self._wire_sampling(),
        )

    def first_token(self) -> int:
        """The token sampled at the KV frontier — the receiver's index-0
        (re-emitted) output."""
        return self.all_tokens[self.num_computed]

    def resume_request(self):
        """Migration-style resume on ANY worker: the full emitted history
        becomes the prompt (a kvbm-attached engine serves the spilled
        blocks as prefix hits), budget shrinks by what was delivered."""
        from ..engine.engine import Request

        return Request(
            request_id=self.seq_id,
            token_ids=self.all_tokens,
            max_tokens=max(1, self.max_tokens - len(self.output_ids)),
            **self._wire_sampling(),
        )


class SeatJournal:
    """Bounded ring of :class:`SeatRecord` keyed by seq id. The cap bounds
    host memory when storms journal faster than resumes consume; the
    oldest record is the one a live resume is least likely to still need."""

    def __init__(self, cap: int = 256):
        self.cap = max(1, cap)
        self._records: Dict[str, SeatRecord] = {}
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._records)

    def record(self, seq, generation: int = 0) -> SeatRecord:
        prev = self._records.get(seq.seq_id)
        if prev is not None:
            generation = max(generation, prev.generation + 1)
        rec = SeatRecord.from_seq(seq, generation=generation)
        self._records.pop(seq.seq_id, None)
        self._records[seq.seq_id] = rec
        while len(self._records) > self.cap:
            oldest = next(iter(self._records))
            del self._records[oldest]
            self.evictions += 1
        return rec

    def pop(self, seq_id: str) -> Optional[SeatRecord]:
        return self._records.pop(seq_id, None)

    def get(self, seq_id: str) -> Optional[SeatRecord]:
        return self._records.get(seq_id)


@dataclass
class EvacResult:
    """One seat's evacuation outcome. ``PEER`` results carry the live
    reservation — stream the continuation with
    ``peer.resume_prefilled(dst_seq, record → first_token)``; every other
    mode resumes from ``record.resume_request()``."""

    record: SeatRecord
    mode: str
    dst_seq: Any = None
    bytes_moved: int = 0


@dataclass
class PreemptionReport:
    notice_lost: bool = False
    deadline_blown: bool = False
    results: List[EvacResult] = field(default_factory=list)

    def count(self, mode: str) -> int:
        return sum(1 for r in self.results if r.mode == mode)


class PreemptionCoordinator:
    """Maintenance-notice listener + evacuating drain for one engine.

    ``peer`` is a co-resident decode engine to receive KV (the launcher's
    P/D pairs, or the chaos harness's second engine); ``host_pool`` /
    ``remote`` are the no-peer spill tiers (default: the engine's attached
    kvbm manager's, when present). ``on_event`` receives the planner-bound
    ``preemption`` event dict.
    """

    def __init__(
        self,
        engine,
        *,
        worker_key: str = "worker",
        peer=None,
        plane=None,
        host_pool=None,
        remote=None,
        notice_grace_s: float = 2.0,
        evac_deadline_s: float = 30.0,
        journal_cap: int = 256,
        on_event: Optional[Callable[[dict], None]] = None,
    ):
        self.engine = engine
        self.worker_key = worker_key
        self.peer = peer
        self.plane = plane
        self._host_pool = host_pool
        self._remote = remote
        self.notice_grace_s = notice_grace_s
        self.evac_deadline_s = evac_deadline_s
        self.journal = SeatJournal(journal_cap)
        self.on_event = on_event
        self.num_notices = 0
        self.num_evacuated = 0
        self.num_spilled = 0
        self.num_fallbacks = 0
        self._noticed = False

    # ------------------------- notice entry ----------------------------

    async def notice(self, reason: str = "maintenance") -> PreemptionReport:
        """Handle a maintenance notice: journal + grace + evacuate.

        Idempotent per process lifetime — a second notice while the first
        drain runs (or after it) returns an empty report instead of
        double-evacuating."""
        report = PreemptionReport()
        rule = faults.active("preempt.notice", self.worker_key)
        if rule is not None and rule.kind == faults.DROP:
            # the notice never reached us: the kill will land cold and
            # recovery rides the journal/migration path alone
            log.warning("maintenance notice LOST (fault injection)")
            report.notice_lost = True
            return report
        if self._noticed:
            return report
        self._noticed = True
        self.num_notices += 1
        seats = self.engine.evacuable_seats()
        log.warning(
            "maintenance notice (%s): %d evacuable seats, grace %.1fs, "
            "deadline %.1fs", reason, len(seats), self.notice_grace_s,
            self.evac_deadline_s,
        )
        if self.on_event is not None:
            try:  # proactive scale signal for the planner
                self.on_event({
                    "kind": "preemption",
                    "worker": self.worker_key,
                    "reason": reason,
                    "seats": len(seats),
                })
            except Exception:
                log.exception("preemption planner event failed")
        # journal BEFORE the grace wait: if the kill beats the deadline,
        # the records already hold everything a cold resume needs
        for seq in seats:
            self.journal.record(seq)
        if self.notice_grace_s > 0:
            await asyncio.sleep(self.notice_grace_s)
        await self.evacuate(report)
        return report

    # --------------------------- evacuation ----------------------------

    async def evacuate(
        self, report: Optional[PreemptionReport] = None
    ) -> PreemptionReport:
        """Evacuate every evacuable seat within ``evac_deadline_s``. Seats
        the deadline cuts off are finished locally on their journal record
        (mode ``FALLBACK``) — bounded wait, nothing leaks."""
        report = report or PreemptionReport()
        deadline = time.monotonic() + self.evac_deadline_s
        for seq in self.engine.evacuable_seats():
            budget = deadline - time.monotonic()
            if budget <= 0:
                report.deadline_blown = True
                report.results.append(self._fallback(seq))
                continue
            try:
                res = await asyncio.wait_for(
                    self._evacuate_seat(seq), timeout=budget
                )
            except asyncio.TimeoutError:
                report.deadline_blown = True
                res = self._fallback(seq)
            except Exception:
                log.exception("evacuating seat %s failed", seq.seq_id)
                res = self._fallback(seq)
            report.results.append(res)
        log.info(
            "evacuation done: %d peer, %d spill, %d fallback, %d finished",
            report.count(PEER), report.count(SPILL),
            report.count(FALLBACK), report.count(FINISHED),
        )
        return report

    async def _evacuate_seat(self, seq) -> EvacResult:
        parked = self.engine.park_for_evacuation(seq.seq_id)
        if parked is None:
            # raced a natural finish (or an abort) — the journal record
            # is stale; whatever happened already flushed to the client
            return EvacResult(record=self.journal.record(seq), mode=FINISHED)
        if not await self.engine.wait_quiesced(seq):
            self.engine.unpark(seq)
            raise RuntimeError(f"seat {seq.seq_id} never quiesced")
        if seq.status.name == "FINISHED":
            # an inflight window landed the seat's final token while we
            # quiesced: its blocks are already freed, nothing to move
            return EvacResult(record=self.journal.record(seq),
                              mode=FINISHED)
        # re-journal at the quiesced frontier: num_computed is now stable
        # and output_ids include every token that will reach the client
        rec = self.journal.record(seq)
        rule = faults.active("preempt.evacuate", seq.seq_id)
        if rule is not None:
            if rule.kind == faults.DROP:
                log.warning("evacuation of %s dropped (fault injection)",
                            seq.seq_id)
                return self._fallback(seq)
            await faults.maybe_delay(rule)
        if self.peer is not None:
            res = await self._to_peer(seq, rec)
            if res is not None:
                return res
        if self._spill_pool() is not None:
            res = await self._to_host(seq, rec)
            if res is not None:
                return res
        return self._fallback(seq)

    async def _to_peer(self, seq, rec: SeatRecord) -> Optional[EvacResult]:
        """Stream the seat's KV into an epoch-guarded peer reservation."""
        dst_seq = self.peer.reserve_sequence(rec.peer_request())
        if dst_seq is None:
            log.warning("peer pool cannot host seat %s — spilling",
                        seq.seq_id)
            return None
        try:
            plane = self.plane
            if plane is None:
                from ..disagg.ici import DevicePlane

                plane = self.plane = DevicePlane()
            nb = len(dst_seq.block_table)
            moved = await plane.transfer(
                self.engine, list(seq.block_table[:nb]),
                self.peer, list(dst_seq.block_table),
                dst_seq_id=dst_seq.seq_id, dst_epoch=dst_seq.kv_epoch,
            )
        except asyncio.CancelledError:
            # deadline cancelled us mid-transfer: the reservation must not
            # outlive the attempt or it leaks on the receiver
            self.peer.cancel_reservation(dst_seq)
            raise
        except Exception:
            log.exception("device transfer for seat %s failed", seq.seq_id)
            self.peer.cancel_reservation(dst_seq)
            return None
        self.engine.finish_evacuated(seq)
        self.num_evacuated += 1
        return EvacResult(record=rec, mode=PEER, dst_seq=dst_seq,
                          bytes_moved=moved)

    async def _to_host(self, seq, rec: SeatRecord) -> Optional[EvacResult]:
        """No peer: spill the seat's sealed blocks to the host pool (and
        the remote tier), so the resume's prefill is mostly cache hits."""
        pool = self._spill_pool()
        bs = self.engine.config.block_size
        nsealed = min(seq.num_computed // bs, len(seq.block_table))
        if seq.token_seq is not None:
            nsealed = min(nsealed, len(seq.token_seq.blocks))
        if nsealed == 0 or seq.token_seq is None:
            return None
        try:
            data = await self.engine.extract_kv_blocks(
                list(seq.block_table[:nsealed])
            )
        except Exception:
            log.exception("KV extract for seat %s failed", seq.seq_id)
            return None
        moved = 0
        for i in range(nsealed):
            block = {
                "k": data["k"][:, i].copy(),
                "v": data["v"][:, i].copy(),
            }
            moved += block["k"].nbytes + block["v"].nbytes
            h = seq.token_seq.blocks[i].sequence_hash
            pool.put(h, block)
            if self._remote is not None:
                try:
                    await self._remote.put(h, block)
                except Exception:
                    log.exception("remote spill failed for %x", h)
        self.engine.finish_evacuated(seq)
        self.num_spilled += 1
        return EvacResult(record=rec, mode=SPILL, bytes_moved=moved)

    def _fallback(self, seq) -> EvacResult:
        """Hand-off failed or out of time: close the seat locally; the
        journal record alone resumes it (full re-prefill) elsewhere."""
        rec = self.journal.get(seq.seq_id) or self.journal.record(seq)
        self.engine.unpark(seq)  # no-op unless parked
        self.engine.finish_evacuated(seq)
        self.num_fallbacks += 1
        return EvacResult(record=rec, mode=FALLBACK)

    def _spill_pool(self):
        if self._host_pool is not None:
            return self._host_pool
        kvbm = getattr(self.engine, "kvbm", None)
        if kvbm is not None:
            if self._remote is None:
                self._remote = kvbm.remote
            return kvbm.host_pool
        return None


def install_preemption_signal(
    coordinator: PreemptionCoordinator,
    *,
    loop: Optional[asyncio.AbstractEventLoop] = None,
    sig: int = _signal.SIGUSR1,
    then: Optional[Callable[[], None]] = None,
) -> None:
    """Wire the cloud maintenance notice (delivered as ``SIGUSR1`` by the
    node agent) to the coordinator. SIGTERM stays with
    ``runtime.signals`` — termination is a drain, a notice is a move.
    ``then`` runs after the evacuation settles (serving chains the
    graceful drain there: evacuate first, then leave)."""
    loop = loop or asyncio.get_running_loop()

    async def _notice() -> None:
        await coordinator.notice("signal")
        if then is not None:
            then()

    loop.add_signal_handler(
        sig, lambda: spawn_logged(_notice(), name="preempt-notice")
    )
