"""Active health checks: canary probes through the real generate path
(ref: lib/runtime/src/health_check.rs:20,44 — per-endpoint
``health_check_payload`` driven by ``DYN_HEALTH_CHECK_*``; here
``DYNTPU_HEALTH_CHECK_*`` via RuntimeConfig).

A passive ``/health`` probe can report healthy while the engine silently
stopped producing tokens; the canary actually exercises the handler. Each
target gets a periodic probe coroutine; consecutive failures past the
threshold flip it unhealthy (visible in the system server and in an optional
``on_unhealthy`` callback — the worker uses that to stop advertising itself
before the lease would expire).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, Optional

from ..utils.logging import get_logger

log = get_logger("health_check")

ProbeFn = Callable[[], Awaitable[None]]   # raises on failure


@dataclass
class HealthCheckConfig:
    period_s: float = 10.0
    timeout_s: float = 5.0
    failure_threshold: int = 3   # consecutive failures → unhealthy


@dataclass
class TargetState:
    healthy: bool = True
    consecutive_failures: int = 0
    probes: int = 0
    last_ok: Optional[float] = None
    last_error: Optional[str] = None


class HealthCheckManager:
    """Runs canary probes for registered targets on a shared schedule."""

    def __init__(self, config: Optional[HealthCheckConfig] = None,
                 on_unhealthy: Optional[Callable[[str], None]] = None,
                 on_recovered: Optional[Callable[[str], None]] = None):
        self.config = config or HealthCheckConfig()
        self.on_unhealthy = on_unhealthy
        # fires on the unhealthy→healthy flip; a router wires these two into
        # its breaker registry (trip / record_success) so canary state and
        # routing agree
        self.on_recovered = on_recovered
        self._targets: Dict[str, ProbeFn] = {}
        self.states: Dict[str, TargetState] = {}
        self._task: Optional[asyncio.Task] = None

    def register(self, name: str, probe: ProbeFn) -> None:
        self._targets[name] = probe
        self.states[name] = TargetState()

    def unregister(self, name: str) -> None:
        self._targets.pop(name, None)
        self.states.pop(name, None)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    @property
    def healthy(self) -> bool:
        return all(s.healthy for s in self.states.values())

    def status(self, name: str) -> dict:
        """System-server probe payload for one target."""
        s = self.states.get(name)
        if s is None:
            return {"healthy": False, "error": "unknown target"}
        return {
            "healthy": s.healthy,
            "probes": s.probes,
            "consecutive_failures": s.consecutive_failures,
            "last_ok": s.last_ok,
            "last_error": s.last_error,
        }

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.period_s)
            for name, probe in list(self._targets.items()):
                await self._probe_once(name, probe)

    async def _probe_once(self, name: str, probe: ProbeFn) -> None:
        state = self.states.get(name)
        if state is None:
            return
        state.probes += 1
        try:
            await asyncio.wait_for(probe(), self.config.timeout_s)
        except Exception as e:
            state.consecutive_failures += 1
            state.last_error = repr(e)
            log.warning("canary %s failed (%d/%d): %r", name,
                        state.consecutive_failures,
                        self.config.failure_threshold, e)
            if (state.healthy and state.consecutive_failures
                    >= self.config.failure_threshold):
                state.healthy = False
                log.error("target %s is UNHEALTHY", name)
                if self.on_unhealthy is not None:
                    self.on_unhealthy(name)
            return
        state.consecutive_failures = 0
        state.last_ok = time.time()
        if not state.healthy:
            log.info("target %s recovered", name)
            state.healthy = True
            if self.on_recovered is not None:
                self.on_recovered(name)


def engine_canary(engine, payload: Optional[dict] = None) -> ProbeFn:
    """Canary through the real generate path (one greedy token, no cache
    pollution beyond a single trash-able block)."""
    payload = payload or {"token_ids": [1], "max_tokens": 1,
                          "ignore_eos": True}

    async def probe() -> None:
        from .context import Context

        got = False
        async for _ in engine.generate(dict(payload), Context()):
            got = True
            break
        if not got:
            raise RuntimeError("canary produced no output")

    return probe
