"""Leader/worker barrier: cluster bring-up rendezvous over the store.

The leader posts payload data under ``v1/barrier/{id}/data`` and waits until
``num_workers`` keys exist under ``v1/barrier/{id}/worker/``; each worker
posts its own key, reads the data, then waits for ``v1/barrier/{id}/complete``
(ref: lib/runtime/src/utils/leader_worker_barrier.rs:125,218). Used for
multi-host mesh bring-up and KVBM leader/worker coordination.
"""

from __future__ import annotations

import msgpack

from .component import BARRIER_ROOT
from .store import StoreClient


class LeaderBarrier:
    def __init__(self, barrier_id: str, num_workers: int, timeout_s: float = 120.0):
        self.barrier_id = barrier_id
        self.num_workers = num_workers
        self.timeout_s = timeout_s

    async def sync(self, store: StoreClient, data: object) -> list[dict]:
        """Publish data, wait for all workers, mark complete.
        Returns each worker's posted payload."""
        root = f"{BARRIER_ROOT}{self.barrier_id}/"
        await store.put(
            root + "data",
            msgpack.packb(data, use_bin_type=True),
            lease=store.primary_lease,
        )
        kvs = await store.wait_for_key_count(
            root + "worker/", self.num_workers, timeout_s=self.timeout_s
        )
        await store.put(root + "complete", b"1", lease=store.primary_lease)
        return [msgpack.unpackb(v, raw=False) for _k, v in kvs]


class WorkerBarrier:
    def __init__(self, barrier_id: str, worker_id: str, timeout_s: float = 120.0):
        self.barrier_id = barrier_id
        self.worker_id = worker_id
        self.timeout_s = timeout_s

    async def sync(self, store: StoreClient, payload: object = None) -> object:
        """Wait for leader data, post our key, wait for completion.
        Returns the leader's data."""
        root = f"{BARRIER_ROOT}{self.barrier_id}/"
        [( _k, raw)] = await store.wait_for_key_count(
            root + "data", 1, timeout_s=self.timeout_s
        )
        await store.put(
            root + f"worker/{self.worker_id}",
            msgpack.packb(payload, use_bin_type=True),
            lease=store.primary_lease,
        )
        await store.wait_for_key_count(root + "complete", 1, timeout_s=self.timeout_s)
        return msgpack.unpackb(raw, raw=False)
