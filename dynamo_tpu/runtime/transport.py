"""Request-push + response-stream transport over TCP with a two-part codec.

The data plane between routers and workers. The reference pushes requests over
NATS and streams responses back over a separate TCP connection with a
length-prefixed two-part (header + payload) codec (ref: lib/runtime/src/
pipeline/network/egress/addressed_router.rs:29-161, tcp/server.rs:62,
codec/two_part.rs:11,157). TPU-native redesign: routers hold pooled,
multiplexed TCP connections directly to worker ingress servers — one
round-trip fewer than the NATS-push-then-TCP-connect-back handshake, same
capability (streaming, cancellation, backpressure via TCP flow control).

Frames are msgpack with a 4-byte length prefix (shared with the store codec).
Two-part shape preserved: a small control header dict + an opaque ``payload``
bytes field that hot paths pass through without re-encoding.

Frame types:
  client → server:  {t: "req",    rid, headers: {...}, payload: bytes}
                    {t: "cancel", rid, kill: bool}
  server → client:  {t: "data",   rid, payload: bytes}
                    {t: "end",    rid}          (stream complete sentinel)
                    {t: "err",    rid, error, code}
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import AsyncIterator, Awaitable, Callable, Dict, Optional

import msgpack

from .. import tracing
from ..utils.logging import TraceContext, get_logger
from . import faults
from .context import Context
from .engine import AsyncEngine
from .store import read_frame, write_frame

log = get_logger("transport")

# error codes surfaced to the Migration operator's retry policy
ERR_APP = "application"          # handler raised — not retryable
ERR_UNAVAILABLE = "unavailable"  # connect failed / conn dropped — retryable
ERR_OVERLOADED = "overloaded"    # worker rejected (busy threshold) — retryable
ERR_TIMEOUT = "deadline_exceeded"  # request deadline hit — NOT retryable
# planned drain: retryable divert-elsewhere, but NOT a failure signal — the
# router must never feed a draining rejection into a circuit breaker
ERR_DRAINING = "draining"

# request header carrying the remaining deadline budget in milliseconds;
# relative (not absolute) so clocks never need to agree across hosts
DEADLINE_HEADER = "x-deadline-ms"


class EngineError(RuntimeError):
    def __init__(self, message: str, code: str = ERR_APP):
        super().__init__(message)
        self.code = code


class IngressServer:
    """Worker-side endpoint server: accepts pushed requests, runs the handler
    engine, streams responses back (ref: pipeline/network/ingress/
    push_endpoint.rs)."""

    def __init__(
        self,
        engine: AsyncEngine,
        host: str = "0.0.0.0",
        port: int = 0,
        max_inflight: Optional[int] = None,
    ):
        self._engine = engine
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._inflight: Dict[str, asyncio.Task] = {}
        self._contexts: Dict[str, Context] = {}
        self._conn_writers: set = set()
        # plain counter, not a Semaphore: admission check + increment happen
        # atomically within one event-loop step, so there is no
        # check-then-acquire race window between concurrent requests
        self._max_inflight = max_inflight
        self._active = 0
        self.draining = False

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        for task in list(self._inflight.values()):
            task.cancel()
        for writer in list(self._conn_writers):
            writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def join(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for in-flight requests to finish (graceful shutdown drain).
        Returns False when ``timeout_s`` elapsed with requests still live."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while self._inflight:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            await asyncio.wait(list(self._inflight.values()), timeout=remaining)
        return True

    async def drain(self, deadline_s: Optional[float] = None,
                    stop_grace_s: float = 2.0) -> bool:
        """Graceful drain: reject new work as ``draining``, wait for in-flight
        streams up to ``deadline_s``, then stop the stragglers gracefully.

        A deadline-stopped stream emits its tokens-so-far and ends WITHOUT a
        ``finished`` marker, which the client's Migration operator re-issues
        on another worker with token carryover — in-flight decodes migrate
        instead of dying. Returns True when fully drained."""
        self.draining = True
        if await self.join(deadline_s):
            return True
        log.warning(
            "drain deadline (%.1fs) hit with %d in-flight — stopping "
            "streams so clients migrate", deadline_s, len(self._inflight),
        )
        for ctx in list(self._contexts.values()):
            ctx.stop_generating()
        return await self.join(stop_grace_s)

    @property
    def num_inflight(self) -> int:
        return len(self._inflight)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        write_lock = asyncio.Lock()
        conn_rids: set = set()
        self._conn_writers.add(writer)
        try:
            while True:
                msg = await read_frame(reader)
                if msg is None:
                    break
                t = msg.get("t")
                if t == "req":
                    rid = msg["rid"]
                    conn_rids.add(rid)
                    task = asyncio.create_task(
                        self._run_request(msg, writer, write_lock)
                    )
                    self._inflight[rid] = task
                    task.add_done_callback(
                        lambda _t, rid=rid: (
                            self._inflight.pop(rid, None),
                            self._contexts.pop(rid, None),
                            conn_rids.discard(rid),
                        )
                    )
                elif t == "cancel":
                    ctx = self._contexts.get(msg["rid"])
                    if ctx is not None:
                        if msg.get("kill"):
                            ctx.kill()
                        else:
                            ctx.stop_generating()
                elif t == "ping":
                    async with write_lock:
                        write_frame(writer, {"t": "pong"})
                        await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        except Exception:  # malformed frame / codec garbage: drop the conn
            log.warning("dropping ingress connection after bad frame",
                        exc_info=True)
        finally:
            # peer gone: kill every stream that was feeding this connection
            for rid in conn_rids:
                ctx = self._contexts.get(rid)
                if ctx is not None:
                    ctx.kill()
            self._conn_writers.discard(writer)
            writer.close()

    async def _run_request(
        self, msg: dict, writer: asyncio.StreamWriter, write_lock: asyncio.Lock
    ) -> None:
        rid = msg["rid"]

        async def send(obj: dict) -> None:
            async with write_lock:
                write_frame(writer, obj)
                await writer.drain()

        # admission control BEFORE any per-request state is registered: a
        # rejected request must leave no context/accounting behind
        if self.draining:
            await send({"t": "err", "rid": rid, "error": "draining",
                        "code": ERR_DRAINING})
            return
        fault = faults.active("worker.admit", rid)
        if fault is not None and fault.kind == faults.REJECT:
            await send({"t": "err", "rid": rid,
                        "error": "injected rejection", "code": fault.code})
            return
        if self._max_inflight is not None and self._active >= self._max_inflight:
            await send({"t": "err", "rid": rid, "error": "worker overloaded",
                        "code": ERR_OVERLOADED})
            return
        self._active += 1
        ctx: Optional[Context] = None
        span = None
        try:
            headers = msg.get("headers") or {}
            if not isinstance(headers, dict):
                headers = {}
            trace = None
            tp = headers.get("traceparent")
            if isinstance(tp, str):
                trace = TraceContext.parse(tp)
            # the worker's process-local root span adopts a child of the wire
            # trace context, so engine spans recorded under ctx parent here
            # while the span itself parents under the client's transport.send
            ing_trace = trace.child() if trace is not None else None
            span = tracing.get_tracer().start_span(
                "worker.ingress", trace=ing_trace,
                parent_span_id=(trace.span_id if trace is not None else None),
                attrs={"rid": rid}, root=True,
            )
            if ing_trace is None:
                ing_trace = TraceContext(
                    trace_id=span.trace_id, span_id=span.span_id
                )
            deadline = None
            budget_ms = headers.get(DEADLINE_HEADER)
            if isinstance(budget_ms, (int, float)):
                deadline = time.monotonic() + float(budget_ms) / 1000.0
            ctx = Context(request_id=headers.get("x-request-id") or rid,
                          trace=ing_trace, deadline=deadline)
            self._contexts[rid] = ctx
            if ctx.is_expired():
                # dead on arrival: never start generating for a request
                # whose client has already given up
                span.set_status("error", "deadline_on_arrival")
                await send({"t": "err", "rid": rid,
                            "error": "deadline expired before start",
                            "code": ERR_TIMEOUT})
                return
            request = msgpack.unpackb(msg["payload"], raw=False)
            async for item in self._engine.generate(request, ctx):
                if ctx.is_killed():
                    break
                if ctx.is_expired():
                    # stop worker-side generation: free the slot, tell the
                    # client the budget is gone (not retryable upstream)
                    ctx.stop_generating()
                    span.set_status("error", ERR_TIMEOUT)
                    await send({"t": "err", "rid": rid,
                                "error": "deadline exceeded mid-stream",
                                "code": ERR_TIMEOUT})
                    return
                fault = await faults.maybe_delay(
                    faults.active("worker.stream", rid)
                )
                if fault is not None and fault.kind == faults.TRUNCATE:
                    # simulate a worker crash: the connection dies abruptly
                    # mid-stream, taking every stream on it down
                    span.set_status("error", "injected_crash")
                    ctx.kill()
                    writer.close()
                    return
                await send(
                    {"t": "data", "rid": rid,
                     "payload": msgpack.packb(item, use_bin_type=True)}
                )
            if not ctx.is_killed():
                await send({"t": "end", "rid": rid})
        except asyncio.CancelledError:
            raise
        except (ConnectionResetError, BrokenPipeError):
            if ctx is not None:
                ctx.kill()
            if span is not None:
                span.set_status("error", "connection_lost")
        except EngineError as exc:
            if span is not None:
                span.set_status("error", exc.code)
            try:
                await send({"t": "err", "rid": rid, "error": str(exc),
                            "code": exc.code})
            except (ConnectionResetError, BrokenPipeError):
                pass
        except Exception as exc:  # noqa: BLE001
            log.exception("handler failed for request %s", rid)
            if span is not None:
                span.set_status("error", ERR_APP)
            try:
                await send({"t": "err", "rid": rid, "error": str(exc),
                            "code": ERR_APP})
            except (ConnectionResetError, BrokenPipeError):
                pass
        finally:
            if span is not None:
                span.end()
            self._active -= 1


class _Conn:
    """One multiplexed client connection with a demux reader."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.streams: Dict[str, asyncio.Queue] = {}
        self.write_lock = asyncio.Lock()
        self.reader_task: Optional[asyncio.Task] = None
        self.closed = False

    async def demux(self) -> None:
        while True:
            msg = await read_frame(self.reader)
            if msg is None:
                break
            q = self.streams.get(msg.get("rid"))
            if q is not None:
                q.put_nowait(msg)
        self.closed = True
        for q in self.streams.values():
            q.put_nowait(None)

    def close(self) -> None:
        self.closed = True
        if self.reader_task:
            self.reader_task.cancel()
        self.writer.close()


class TransportClient:
    """Router-side client: pooled multiplexed connections keyed by address."""

    def __init__(self):
        self._conns: Dict[str, _Conn] = {}
        self._rids = itertools.count(1)
        self._conn_locks: Dict[str, asyncio.Lock] = {}

    async def _get_conn(self, addr: str) -> _Conn:
        lock = self._conn_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            fault = await faults.maybe_delay(faults.active("client.connect", addr))
            if fault is not None and fault.kind in (faults.DROP, faults.REJECT):
                raise EngineError(
                    f"cannot connect to worker at {addr}: injected fault",
                    ERR_UNAVAILABLE,
                )
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
            host, port = addr.rsplit(":", 1)
            try:
                reader, writer = await asyncio.open_connection(host, int(port))
            except OSError as exc:
                raise EngineError(
                    f"cannot connect to worker at {addr}: {exc}", ERR_UNAVAILABLE
                ) from exc
            conn = _Conn(reader, writer)
            conn.reader_task = asyncio.create_task(conn.demux())
            self._conns[addr] = conn
            return conn

    async def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()

    async def generate(
        self, addr: str, request: object, context: Context
    ) -> AsyncIterator[object]:
        """Push a request to ``addr``; yield the response stream.

        Raises :class:`EngineError` with a retryability code — the Migration
        operator upstream decides whether to re-issue (ref: migration.rs:88).
        """
        remaining = context.time_remaining()
        if remaining is not None and remaining <= 0:
            raise EngineError(
                f"deadline expired before dispatch to {addr}", ERR_TIMEOUT
            )
        # the wire trace context IS the transport span: the worker parses it
        # from the traceparent header and parents its ingress span under it
        wire = context.trace.child()
        span = tracing.get_tracer().start_span(
            "transport.send", trace=wire,
            parent_span_id=context.trace.span_id, attrs={"addr": addr},
        )

        def _fail_span(code: str) -> None:
            if not span.ended:
                span.set_status("error", code)
                span.end()

        try:
            conn = await self._get_conn(addr)
        except EngineError as e:
            _fail_span(e.code)
            raise
        rid = f"{context.id}-{next(self._rids)}"
        queue: asyncio.Queue = asyncio.Queue()
        conn.streams[rid] = queue
        headers = {
            "traceparent": wire.traceparent(),
            "x-request-id": context.id,
        }
        if remaining is not None:
            headers[DEADLINE_HEADER] = int(remaining * 1000)
        fault = faults.active("client.send", addr)
        if fault is not None and fault.kind in (faults.DROP, faults.REJECT):
            conn.streams.pop(rid, None)
            _fail_span(ERR_UNAVAILABLE)
            raise EngineError(
                f"worker {addr} send failed: injected fault", ERR_UNAVAILABLE
            )
        try:
            async with conn.write_lock:
                write_frame(
                    conn.writer,
                    {"t": "req", "rid": rid, "headers": headers,
                     "payload": msgpack.packb(request, use_bin_type=True)},
                )
                await conn.writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError) as exc:
            conn.streams.pop(rid, None)
            conn.close()
            _fail_span(ERR_UNAVAILABLE)
            raise EngineError(f"worker {addr} send failed: {exc}", ERR_UNAVAILABLE)

        # One long-lived watcher per stream injects a sentinel into the demux
        # queue when cancellation fires, so the per-token hot loop below is a
        # single queue.get() — no task creation per streamed item (the
        # reference keeps this path equally lean, ref: tcp/client.rs).
        _STOPPED = {"t": "_stopped"}

        async def _watch_stop() -> None:
            await context.wait_stopped()
            queue.put_nowait(_STOPPED)
            if not context.is_killed():
                # stop → kill escalation mid-drain needs a second wakeup
                await context.wait_killed()
                queue.put_nowait(_STOPPED)

        stop_task = asyncio.create_task(_watch_stop())
        cancel_sent = False
        try:
            while True:
                budget = context.time_remaining()
                if budget is None:
                    msg = await queue.get()
                else:
                    # a stalled worker must not outlive the request budget:
                    # bound the wait by the remaining deadline, then tell
                    # the worker to abandon the stream
                    try:
                        msg = await asyncio.wait_for(
                            queue.get(), max(budget, 0.001)
                        )
                    except asyncio.TimeoutError:
                        cancel_sent = True
                        await self._send_cancel(conn, rid, True)
                        _fail_span(ERR_TIMEOUT)
                        raise EngineError(
                            f"worker {addr} exceeded the request deadline",
                            ERR_TIMEOUT,
                        )
                if msg is None:
                    _fail_span(ERR_UNAVAILABLE)
                    raise EngineError(
                        f"worker {addr} connection dropped mid-stream",
                        ERR_UNAVAILABLE,
                    )
                t = msg.get("t")
                if t == "_stopped":
                    if context.is_killed():
                        cancel_sent = True
                        await self._send_cancel(conn, rid, True)
                        return
                    if not cancel_sent:
                        cancel_sent = True
                        await self._send_cancel(conn, rid, False)
                    # graceful stop: keep draining until the worker ends the
                    # stream (it emits the tokens generated so far)
                    continue
                if t == "data":
                    if not span.ended:
                        # the span measures push → first response frame;
                        # token streaming after that belongs to the engine
                        span.add_event("first_frame")
                        span.end()
                    yield msgpack.unpackb(msg["payload"], raw=False)
                elif t == "end":
                    span.end()
                    return
                elif t == "err":
                    _fail_span(msg.get("code", ERR_APP))
                    raise EngineError(
                        msg.get("error", "worker error"),
                        msg.get("code", ERR_APP),
                    )
        finally:
            stop_task.cancel()
            conn.streams.pop(rid, None)
            _fail_span("closed_before_first_frame")
            if (context.is_stopped() or context.is_killed()) and not cancel_sent:
                await self._send_cancel(conn, rid, context.is_killed())

    async def _send_cancel(self, conn: _Conn, rid: str, kill: bool) -> None:
        if conn.closed:
            return
        try:
            async with conn.write_lock:
                write_frame(conn.writer, {"t": "cancel", "rid": rid, "kill": kill})
                await conn.writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
