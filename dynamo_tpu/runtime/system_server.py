"""System status server: /health, /live, /metrics for any runtime process.

Role-equivalent to the reference's axum system server (ref: lib/runtime/src/
system_status_server.rs, enabled by DYN_SYSTEM_ENABLED/PORT — here
``DYNTPU_SYSTEM_ENABLED`` / ``DYNTPU_SYSTEM_PORT`` via RuntimeConfig). Health
aggregates registered probe callbacks (engines, endpoints) so orchestrators
can gate traffic on worker readiness.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from aiohttp import web

from ..utils.logging import get_logger
from ..utils.metrics import MetricsRegistry

log = get_logger("system_server")

HealthProbe = Callable[[], dict]   # () -> {"healthy": bool, ...detail}


class SystemServer:
    def __init__(
        self,
        metrics: Optional[MetricsRegistry] = None,
        host: str = "0.0.0.0",
        port: int = 0,
        store=None,
    ):
        self.metrics = metrics
        self.host = host
        self.port = port
        # this process's StoreClient (when the owning runtime has one):
        # fault-plan installs kick clock-gated rules through it so chaos
        # replays fire deterministically (see _faults_install)
        self.store = store
        self._probes: Dict[str, HealthProbe] = {}
        # admin drain triggers: name -> zero-arg callable kicking off a
        # graceful drain (same path as SIGINT/SIGTERM)
        self._drain_handlers: Dict[str, Callable[[], None]] = {}
        # maintenance-notice triggers: name -> zero-arg callable kicking
        # off an evacuating drain (runtime.preemption)
        self._preempt_handlers: Dict[str, Callable[[], None]] = {}
        self._live = True
        self._runner: Optional[web.AppRunner] = None

    def register_probe(self, name: str, probe: HealthProbe) -> None:
        self._probes[name] = probe

    def unregister_probe(self, name: str) -> None:
        self._probes.pop(name, None)

    def register_drain(self, name: str, handler: Callable[[], None]) -> None:
        self._drain_handlers[name] = handler

    def register_preempt(self, name: str,
                         handler: Callable[[], None]) -> None:
        self._preempt_handlers[name] = handler

    def set_live(self, live: bool) -> None:
        self._live = live

    async def start(self) -> None:
        app = web.Application()
        app.add_routes([
            web.get("/health", self._health),
            web.get("/live", self._livez),
            web.post("/drain", self._drain),
            web.post("/preempt", self._preempt),
            web.get("/metrics", self._metrics),
            web.get("/debug/profile", self._profile),
            web.get("/debug/traces", self._traces),
            web.get("/debug/traces/{trace_id}", self._trace),
            web.get("/debug/faults", self._faults_get),
            web.post("/debug/faults", self._faults_install),
            web.delete("/debug/faults", self._faults_clear),
        ])
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            server = getattr(s, "_server", None)
            if server and server.sockets:
                self.port = server.sockets[0].getsockname()[1]
        log.info("system server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    async def _health(self, request: web.Request) -> web.Response:
        detail = {}
        healthy = True
        for name, probe in self._probes.items():
            try:
                r = probe()
            except Exception as e:  # a broken probe is an unhealthy probe
                r = {"healthy": False, "error": str(e)}
            detail[name] = r
            healthy = healthy and bool(r.get("healthy", False))
        status = 200 if healthy or not self._probes else 503
        return web.json_response(
            {"status": "healthy" if status == 200 else "unhealthy",
             "probes": detail},
            status=status,
        )

    async def _drain(self, request: web.Request) -> web.Response:
        """Admin drain trigger: stop routing here, finish or migrate
        in-flight work, then exit clean. 202 — the drain runs async."""
        if not self._drain_handlers:
            return web.json_response(
                {"error": "nothing drainable registered"}, status=404
            )
        fired = []
        for name, handler in list(self._drain_handlers.items()):
            try:
                handler()
                fired.append(name)
            except Exception:
                log.exception("drain handler %s failed", name)
        return web.json_response({"draining": fired}, status=202)

    async def _preempt(self, request: web.Request) -> web.Response:
        """Maintenance-notice trigger (the HTTP twin of the node agent's
        SIGUSR1): evacuate in-flight KV to a peer / the host tier, then
        drain. 202 — the evacuation runs async against its deadline."""
        if not self._preempt_handlers:
            return web.json_response(
                {"error": "nothing preemptible registered"}, status=404
            )
        fired = []
        for name, handler in list(self._preempt_handlers.items()):
            try:
                handler()
                fired.append(name)
            except Exception:
                log.exception("preempt handler %s failed", name)
        return web.json_response({"evacuating": fired}, status=202)

    async def _livez(self, request: web.Request) -> web.Response:
        return web.json_response({"live": self._live},
                                 status=200 if self._live else 503)

    async def _metrics(self, request: web.Request) -> web.Response:
        from prometheus_client import CONTENT_TYPE_LATEST

        body = self.metrics.render() if self.metrics else b""
        # exposition-format content type (text/plain; version=0.0.4) so
        # conformant scrapers negotiate the right parser
        return web.Response(body=body,
                            headers={"Content-Type": CONTENT_TYPE_LATEST})

    async def _profile(self, request: web.Request) -> web.Response:
        """On-demand device profile: ``GET /debug/profile?ms=N`` captures a
        ``jax.profiler`` trace for N ms (clamped) into a TensorBoard-loadable
        directory and returns its path. One capture at a time per process;
        concurrent requests get 409."""
        from ..observability import profiling

        try:
            ms = int(request.query.get("ms", profiling.DEFAULT_MS))
        except ValueError:
            return web.json_response(
                {"error": "ms must be an integer"}, status=400
            )
        try:
            result = await profiling.capture(
                ms, base_dir=request.query.get("dir", "")
            )
        except profiling.ProfileBusyError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:  # profiler unavailable on this backend
            log.exception("profile capture failed")
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(result)

    async def _traces(self, request: web.Request) -> web.Response:
        """Recent trace ids still resident in this process's span buffer."""
        from .. import tracing

        ids = tracing.get_tracer().trace_ids()
        return web.json_response({"trace_ids": ids, "count": len(ids)})

    async def _trace(self, request: web.Request) -> web.Response:
        """Assembled view of one trace (this process's spans only)."""
        from .. import tracing
        from ..tracing.assemble import assemble_trace

        trace_id = request.match_info["trace_id"]
        spans = tracing.get_tracer().get_trace(trace_id)
        if not spans:
            return web.json_response(
                {"error": f"unknown trace id {trace_id!r}"}, status=404
            )
        return web.json_response(assemble_trace([s.to_dict() for s in spans]))

    async def _faults_get(self, request: web.Request) -> web.Response:
        """The installed fault plan (rules + firing log), for replay
        attribution harvest and operator inspection."""
        from . import faults

        plan = faults.current()
        if plan is None:
            return web.json_response({"installed": False})
        return web.json_response(
            {"installed": True, "plan": plan.to_dict(include_log=True),
             "fired_counts": plan.fired_counts()})

    async def _faults_install(self, request: web.Request) -> web.Response:
        """Install (or extend) the process-global fault plan from its wire
        form. A body whose seed matches the installed plan *merges* its
        rules in — how the replay driver lands successive correlated fault
        waves on one process; any other seed (or no installed plan)
        replaces the plan wholesale."""
        from . import faults

        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "body must be JSON"},
                                     status=400)
        try:
            incoming = faults.FaultPlan.from_dict(body)
        except (ValueError, KeyError, TypeError) as exc:
            return web.json_response({"error": str(exc)}, status=400)
        plan = faults.current()
        merged = False
        if plan is not None and plan.seed == incoming.seed:
            for rule in incoming.rules:
                plan.add(rule)
            merged = True
        else:
            plan = incoming
            faults.install(plan)
        log.info("fault plan %s: seed=%d rules=%d",
                 "merged" if merged else "installed", plan.seed,
                 len(plan.rules))
        # lease keepalives are wall-clock-periodic with a phase set at
        # client spawn, so a finite-times rule gating them would fire a
        # load-dependent 0..times within any replay window. Drive the op
        # directly, once per budgeted firing, so the count is exactly
        # ``times`` in every run (the in-process replay driver does the
        # same — the two modes must fire identically under one seed).
        kicked = 0
        if self.store is not None:
            for rule in incoming.rules:
                if (rule.site == "store.call"
                        and rule.match == "lease_keepalive"):
                    for _ in range(max(1, int(rule.times or 1))):
                        await self.store.kick_keepalive()
                        kicked += 1
        return web.json_response(
            {"installed": True, "merged": merged, "seed": plan.seed,
             "rules": len(plan.rules), "kicked": kicked})

    async def _faults_clear(self, request: web.Request) -> web.Response:
        """Clear the fault plan — or just one wave's rules with ``?wave=``
        (the firing log survives for attribution)."""
        from . import faults

        wave = request.query.get("wave")
        plan = faults.current()
        if plan is None:
            return web.json_response({"installed": False, "removed": 0})
        if wave:
            removed = plan.clear_wave(wave)
            return web.json_response(
                {"installed": True, "wave": wave, "removed": removed})
        removed = len(plan.rules)
        faults.clear()
        return web.json_response({"installed": False, "removed": removed})
