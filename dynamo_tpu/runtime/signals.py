"""Unified SIGINT/SIGTERM shutdown wiring.

Every long-running entrypoint (engine worker, frontend, metrics
aggregator) needs the same three behaviors from its signal handlers:

- the **first** signal triggers exactly one graceful shutdown, no matter
  how many delivery paths exist (two signals registered, plus programmatic
  triggers like ``POST /drain``);
- a **second** signal while the drain is already running means the
  operator wants out *now* — hard-exit immediately instead of waiting on
  an in-flight drain that may be wedged;
- programmatic re-triggers (a second ``POST /drain``) are idempotent
  no-ops, never a hard exit.

``install_shutdown_signals`` returns the :class:`ShutdownGuard` so callers
can share the same once-latch with non-signal triggers.
"""

from __future__ import annotations

import asyncio
import os
import signal as _signal
from typing import Callable, Iterable, Optional

from ..utils.logging import get_logger

log = get_logger("runtime.signals")

DEFAULT_SIGNALS = (_signal.SIGINT, _signal.SIGTERM)


class ShutdownGuard:
    """Once-latch around a shutdown callback.

    ``trigger()`` is the programmatic entry (idempotent); the installed
    signal handler escalates a repeat signal to ``hard_exit(1)``.
    """

    def __init__(
        self,
        on_shutdown: Callable[[], None],
        *,
        name: str = "shutdown",
        hard_exit: Callable[[int], None] = os._exit,
    ):
        self._on_shutdown = on_shutdown
        self._name = name
        self._hard_exit = hard_exit
        self._fired = False

    @property
    def fired(self) -> bool:
        return self._fired

    def trigger(self) -> bool:
        """Fire the shutdown callback once; repeat calls are no-ops.
        Returns True if this call fired it."""
        if self._fired:
            return False
        self._fired = True
        self._on_shutdown()
        return True

    def on_signal(self) -> None:
        """Signal-delivery entry: first signal triggers the shutdown,
        a second one hard-exits (the drain is taking too long or is
        wedged and the operator pressed ^C again)."""
        if self._fired:
            log.warning("%s: repeated signal during shutdown — hard exit",
                        self._name)
            self._hard_exit(1)
            return
        log.info("%s: signal received — shutting down", self._name)
        self.trigger()


def install_shutdown_signals(
    on_shutdown: Callable[[], None],
    *,
    loop: Optional[asyncio.AbstractEventLoop] = None,
    name: str = "shutdown",
    signals: Iterable[int] = DEFAULT_SIGNALS,
    hard_exit: Callable[[int], None] = os._exit,
) -> ShutdownGuard:
    """Register ``on_shutdown`` behind a :class:`ShutdownGuard` on
    ``loop`` for each signal in ``signals`` and return the guard."""
    guard = ShutdownGuard(on_shutdown, name=name, hard_exit=hard_exit)
    loop = loop or asyncio.get_running_loop()
    for sig in signals:
        loop.add_signal_handler(sig, guard.on_signal)
    return guard
