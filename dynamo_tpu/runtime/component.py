"""Namespace → Component → Endpoint → Instance model over the discovery store.

The cluster addressing scheme (ref: lib/runtime/src/component.rs:75-143):
instances register under
``v1/instances/{namespace}/{component}/{endpoint}/{instance_id}`` with their
TCP ingress address, attached to the process's primary lease so worker death
deregisters them automatically. ``Client`` watches that prefix and keeps a
live instance list for routing (ref: component/client.rs:285).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import AsyncIterator, Callable, Dict, List, Optional

import msgpack

from .. import tracing
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger
from ..utils.metrics import MetricsRegistry
from .context import Context
from .engine import AsyncEngine, FnEngine
from .store import StoreClient
from .transport import (
    EngineError, ERR_OVERLOADED, ERR_UNAVAILABLE, IngressServer,
    TransportClient,
)

log = get_logger("component")

INSTANCE_ROOT = "v1/instances/"
MODEL_ROOT = "v1/models/"     # ref: kv_router.rs:36 MODEL_ROOT_PATH
MDC_ROOT = "v1/mdc/"          # model deployment cards
BARRIER_ROOT = "v1/barrier/"


@dataclass(frozen=True)
class Instance:
    instance_id: int
    namespace: str
    component: str
    endpoint: str
    addr: str  # host:port of the worker's TCP ingress

    @property
    def key(self) -> str:
        return (
            f"{INSTANCE_ROOT}{self.namespace}/{self.component}/"
            f"{self.endpoint}/{self.instance_id}"
        )


class DistributedRuntime:
    """Process-local handle on the cluster (ref: lib/runtime/src/lib.rs:145).

    Owns the store client (with primary lease + keepalive), the transport
    client pool, the metrics root, and the shutdown event. Lease loss triggers
    runtime shutdown, matching the reference's liveness contract.
    """

    def __init__(self, store: StoreClient, config: RuntimeConfig):
        self.store = store
        self.config = config
        self.transport = TransportClient()
        self.metrics = MetricsRegistry(prefix="dynamo")
        self.shutdown_event = asyncio.Event()
        self._ingress_servers: List[IngressServer] = []
        self.system_server = None  # started when config.system_enabled
        # (endpoint_path, store_key) pairs written by register_llm so
        # graceful endpoint shutdown also deregisters the models
        self.registered_models: List[tuple] = []
        store.on_lease_lost = self._on_lease_lost
        # per-stage latency histograms from trace spans land in this
        # process's registry regardless of the span-export sampling knob
        tracing.get_tracer().attach_metrics(self.metrics)

    @staticmethod
    async def from_settings(
        config: Optional[RuntimeConfig] = None,
    ) -> "DistributedRuntime":
        config = config or RuntimeConfig.from_settings()
        store = await StoreClient.connect(
            config.store_addr, lease_ttl_s=config.lease_ttl_s,
            recover_timeout_s=config.store_recover_timeout_s,
            reconnect_base_s=config.store_reconnect_base_s,
            reconnect_cap_s=config.store_reconnect_cap_s,
        )
        tracer = tracing.get_tracer()
        tracer.configure(
            sample_ratio=config.trace_sample_ratio,
            slow_threshold_s=config.trace_slow_threshold_s,
            buffer_size=config.trace_buffer_size,
        )
        if config.trace_export_path:
            tracer.add_jsonl(config.trace_export_path)
        runtime = DistributedRuntime(store, config)
        if config.system_enabled:
            await runtime.start_system_server(port=config.system_port)
        return runtime

    async def start_system_server(self, port: int = 0) -> None:
        """Start /health /live /metrics (ref: system_status_server.rs)."""
        from .system_server import SystemServer

        self.system_server = SystemServer(metrics=self.metrics, port=port,
                                          store=self.store)
        await self.system_server.start()

    def _on_lease_lost(self) -> None:
        log.error("primary lease lost — shutting down runtime")
        self.shutdown_event.set()

    @property
    def primary_lease(self) -> int:
        return self.store.primary_lease

    def namespace(self, name: Optional[str] = None) -> "Namespace":
        return Namespace(self, name or self.config.namespace)

    async def shutdown(self) -> None:
        self.shutdown_event.set()
        tracing.get_tracer().detach_metrics(self.metrics)
        if self.system_server is not None:
            self.system_server.set_live(False)
            await self.system_server.stop()
        for srv in self._ingress_servers:
            await srv.stop()
        await self.transport.close()
        await self.store.close()


class Namespace:
    def __init__(self, runtime: DistributedRuntime, name: str):
        self.runtime = runtime
        self.name = name
        self.metrics = runtime.metrics.child(namespace=name)

    def component(self, name: str) -> "Component":
        return Component(self, name)


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name
        self.runtime = namespace.runtime
        self.metrics = namespace.metrics.child(component=name)

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    @property
    def path(self) -> str:
        return f"{self.namespace.name}/{self.name}"

    def event_subject(self, name: str) -> str:
        """Store key prefix used as a pub/sub subject for this component
        (e.g. ``kv_events``, ref: kv_router.rs:60)."""
        return f"v1/events/{self.path}/{name}/"


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name
        self.runtime = component.runtime
        self.metrics = component.metrics.child(endpoint=name)

    @property
    def path(self) -> str:
        return f"{self.component.path}/{self.name}"

    @property
    def instance_prefix(self) -> str:
        return f"{INSTANCE_ROOT}{self.path}/"

    async def serve_endpoint(
        self,
        handler: AsyncEngine | Callable,
        *,
        host: str = "0.0.0.0",
        advertise_host: str = "127.0.0.1",
        port: int = 0,
        max_inflight: Optional[int] = None,
        metadata: Optional[dict] = None,
    ) -> "ServedEndpoint":
        """Start a TCP ingress for ``handler`` and register the instance
        (ref: bindings _core.pyi:216 ``serve_endpoint``)."""
        engine = handler if isinstance(handler, AsyncEngine) else FnEngine(handler)
        server = IngressServer(engine, host=host, port=port, max_inflight=max_inflight)
        await server.start()
        self.runtime._ingress_servers.append(server)
        instance = Instance(
            instance_id=self.runtime.primary_lease,
            namespace=self.component.namespace.name,
            component=self.component.name,
            endpoint=self.name,
            addr=f"{advertise_host}:{server.port}",
        )
        record = {
            "instance_id": instance.instance_id,
            "addr": instance.addr,
            "transport": "tcp",
            "metadata": metadata or {},
        }
        await self.runtime.store.put(
            instance.key,
            msgpack.packb(record, use_bin_type=True),
            lease=self.runtime.primary_lease,
        )
        log.info("serving %s as instance %d at %s",
                 self.path, instance.instance_id, instance.addr)
        if self.runtime.system_server is not None:
            self.runtime.system_server.register_probe(
                self.path,
                lambda: {"healthy": not server.draining,
                         "inflight": server.num_inflight},
            )
        return ServedEndpoint(self, server, instance, record=record)

    async def client(self) -> "Client":
        client = Client(self)
        await client.start()
        return client


class ServedEndpoint:
    def __init__(
        self, endpoint: Endpoint, server: IngressServer, instance: Instance,
        record: Optional[dict] = None,
    ):
        self.endpoint = endpoint
        self.server = server
        self.instance = instance
        # kept so withdraw/readvertise can re-put the exact same record
        self._record = record

    async def drain_and_stop(
        self, deadline_s: Optional[float] = None, stop_grace_s: float = 2.0,
    ) -> None:
        """Graceful shutdown: deregister (no new routing), reject late
        arrivals as ``draining``, finish in-flight within ``deadline_s`` —
        stragglers get their streams stopped so clients migrate — then stop.
        """
        self.server.draining = True
        await self._deregister()
        drained = await self.server.drain(deadline_s, stop_grace_s=stop_grace_s)
        if not drained:
            log.warning(
                "%s: %d streams still in flight after drain — stopping hard",
                self.endpoint.path, self.server.num_inflight,
            )
        await self.server.stop()

    async def stop(self) -> None:
        await self._deregister()
        await self.server.stop()

    async def withdraw(self) -> None:
        """Pull the instance key so the cluster stops routing here, without
        stopping the server (health-probe failure path)."""
        runtime = self.endpoint.runtime
        try:
            await runtime.store.delete(self.instance.key)
        except Exception as exc:
            log.warning("withdraw of %s failed (%s) — store down? the lease "
                        "expiring will deregister us anyway",
                        self.instance.key, exc)

    async def readvertise(self) -> None:
        """Re-put the instance key after health recovery so routing resumes."""
        if self.server.draining:
            return  # a recovered-but-draining worker must stay withdrawn
        runtime = self.endpoint.runtime
        record = self._record or {
            "instance_id": self.instance.instance_id,
            "addr": self.instance.addr,
            "transport": "tcp",
            "metadata": {},
        }
        await runtime.store.put(
            self.instance.key,
            msgpack.packb(record, use_bin_type=True),
            lease=runtime.primary_lease,
        )
        log.info("re-advertised %s after recovery", self.instance.key)

    async def _deregister(self) -> None:
        # a drain must complete even while the store is unreachable: every
        # store op here is best-effort (the lease dying cleans up for us)
        runtime = self.endpoint.runtime
        try:
            await runtime.store.delete(self.instance.key)
        except Exception as exc:
            log.warning("deregister of %s failed: %s", self.instance.key, exc)
        path = self.endpoint.path
        if runtime.system_server is not None:
            runtime.system_server.unregister_probe(path)
        for ep_path, key in list(runtime.registered_models):
            if ep_path == path:
                try:
                    await runtime.store.delete(key)
                except Exception as exc:
                    log.warning("deregister of %s failed: %s", key, exc)
                runtime.registered_models.remove((ep_path, key))


class Client:
    """Watches an endpoint's instance prefix; routes requests to instances
    (ref: component/client.rs:285 + pipeline/network/egress/push_router.rs)."""

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint
        self.runtime = endpoint.runtime
        self.instances: Dict[int, Instance] = {}
        # optional busy gate (ref: push_router.rs:58-63 busy-threshold
        # rejection); installed by router.monitor.WorkerMonitor.attach()
        self.busy_fn: Optional[Callable[[int], bool]] = None
        self._rr = 0
        self._watch_task: Optional[asyncio.Task] = None
        self._watch_stream = None
        self._instances_changed = asyncio.Event()
        self.on_instance_removed: List[Callable[[int], None]] = []
        self.on_instance_added: List[Callable[[int], None]] = []

    async def start(self) -> None:
        # resilient watch: across store outages the stream resyncs itself
        # (revision catch-up or snapshot reconcile) while we keep routing to
        # the last-known instance table (stale-while-revalidate)
        snapshot, stream = await self.runtime.store.watch_prefix_resilient(
            self.endpoint.instance_prefix,
            grace_s=self.runtime.config.store_reconcile_grace_s,
        )
        self._watch_stream = stream
        for key, value in snapshot:
            self._apply("put", key, value)
        self._watch_task = asyncio.create_task(self._watch_loop(stream))

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watch_stream is not None:
            await self._watch_stream.cancel()
            self._watch_stream = None

    def _apply(self, event: str, key: str, value: Optional[bytes]) -> None:
        instance_id = int(key.rsplit("/", 1)[1])
        if event == "put" and value is not None:
            record = msgpack.unpackb(value, raw=False)
            self.instances[instance_id] = Instance(
                instance_id=instance_id,
                namespace=self.endpoint.component.namespace.name,
                component=self.endpoint.component.name,
                endpoint=self.endpoint.name,
                addr=record["addr"],
            )
            for cb in self.on_instance_added:
                cb(instance_id)
        elif event == "delete":
            if self.instances.pop(instance_id, None) is not None:
                for cb in self.on_instance_removed:
                    cb(instance_id)
        self._instances_changed.set()
        self._instances_changed = asyncio.Event()

    async def _watch_loop(self, stream) -> None:
        while True:
            event = await stream.next()
            if event is None:
                return  # client closed for good; lease loss shuts us down
            if event["event"] == "dropped":
                continue  # the resilient stream resyncs; nothing to do here
            self._apply(event["event"], event["key"], event.get("value"))

    def instance_ids(self) -> List[int]:
        return sorted(self.instances.keys())

    async def wait_for_instances(self, n: int = 1, timeout_s: float = 60.0) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while len(self.instances) < n:
            remaining = deadline - loop.time()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self.instances)}/{n} instances"
                )
            event = self._instances_changed
            try:
                await asyncio.wait_for(asyncio.shield(event.wait()), remaining)
            except asyncio.TimeoutError:
                pass

    # -- request push (ref: push_router.rs RouterMode Direct/Random/RoundRobin) --

    def _pick(self, mode: str) -> Instance:
        ids = self.instance_ids()
        if not ids:
            raise EngineError(
                f"no instances for {self.endpoint.path}", ERR_UNAVAILABLE
            )
        if self.busy_fn is not None:
            free = [i for i in ids if not self.busy_fn(i)]
            if not free:
                raise EngineError(
                    f"all {len(ids)} instances of {self.endpoint.path} "
                    "are busy", ERR_OVERLOADED,
                )
            ids = free
        if mode == "random":
            chosen = random.choice(ids)
        else:  # round_robin
            chosen = ids[self._rr % len(ids)]
            self._rr += 1
        return self.instances[chosen]

    def direct(
        self, instance_id: int, request: object, context: Context
    ) -> AsyncIterator[object]:
        instance = self.instances.get(instance_id)
        if instance is None:
            raise EngineError(
                f"instance {instance_id} not found for {self.endpoint.path}",
                ERR_UNAVAILABLE,
            )
        return self.runtime.transport.generate(instance.addr, request, context)

    def round_robin(self, request: object, context: Context) -> AsyncIterator[object]:
        return self.runtime.transport.generate(
            self._pick("round_robin").addr, request, context
        )

    def random(self, request: object, context: Context) -> AsyncIterator[object]:
        return self.runtime.transport.generate(
            self._pick("random").addr, request, context
        )
