"""Lease-KV discovery store: the control-plane brain of the cluster.

Plays the role etcd plays in the reference (ref: lib/runtime/src/transports/
etcd.rs:35-324): a small TCP service holding a revisioned key-value map with

- **leases**: TTL'd handles with keepalive; when a lease dies every key
  attached to it is deleted and watchers are notified — this is the liveness
  mechanism (worker death ⇒ its ``instances/…`` and ``models/…`` keys vanish,
  ref: etcd.rs:89-95),
- **watches**: prefix subscriptions that stream put/delete events,
- **atomic create** (fails if key exists) and compare-and-swap,
- **distributed locks** built on atomic create + leases (ref: etcd.rs:300),
- **barriers** for leader/worker rendezvous (via ``wait_for_key_count``,
  ref: utils/leader_worker_barrier.rs:24).

It also carries the two roles NATS plays in the reference:

- **pub/sub subjects** (no storage, fan-out to live subscribers) for KV
  events and metrics (ref: transports/nats.rs, kv_router.rs:60-66),
- **work queues** (push + blocking pull) used as the disaggregation prefill
  queue (ref: ``NatsQueue`` transports/nats.rs:426).

Framing: 4-byte big-endian length + msgpack body. Requests carry a ``seq``;
responses echo it; watch events are pushed with ``seq: None`` and a
``watch_id``. One asyncio server task per connection; state is single-threaded
within the server loop, so operations are atomic without locks.

Run standalone: ``python -m dynamo_tpu.runtime.store --port 3280``.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import random
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

import msgpack

from ..utils.logging import get_logger
from . import faults

log = get_logger("store")

DEFAULT_PORT = 3280
_MAX_FRAME = 256 * 1024 * 1024
_MAX_SUB_BUFFER = 8 * 1024 * 1024   # slow-subscriber drop threshold
_MAX_ORPHAN_EVENTS = 256            # per unclaimed watch id
_MAX_EVENT_HISTORY = 4096           # retained events for watch rev catch-up


# ------------------------------- framing ---------------------------------


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    size = int.from_bytes(header, "big")
    if size > _MAX_FRAME:
        raise ValueError(f"frame too large: {size}")
    try:
        body = await reader.readexactly(size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False)


def write_frame(writer: asyncio.StreamWriter, obj: dict) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    writer.write(len(body).to_bytes(4, "big") + body)


# ------------------------------- server ----------------------------------


@dataclass
class _Lease:
    lease_id: int
    ttl_s: float
    deadline: float
    keys: set = field(default_factory=set)


@dataclass
class _KvEntry:
    value: bytes
    lease_id: int  # 0 = no lease
    create_rev: int
    mod_rev: int


@dataclass
class _Watch:
    watch_id: int
    prefix: str
    writer: asyncio.StreamWriter


class _WorkQueue:
    """Push/blocking-pull queue (the JetStream work-queue role)."""

    def __init__(self) -> None:
        self.items: List[bytes] = []
        self.waiters: List[asyncio.Future] = []

    def push(self, payload: bytes) -> int:
        while self.waiters:
            fut = self.waiters.pop(0)
            if not fut.done():
                fut.set_result(payload)
                return len(self.items)
        self.items.append(payload)
        return len(self.items)

    def pop_nowait(self) -> Optional[bytes]:
        return self.items.pop(0) if self.items else None


class StoreServer:
    """In-memory revisioned lease-KV store served over TCP.

    With ``persist_path`` set, unleased KV entries, work-queue items, and
    the revision counter are snapshotted to disk (msgpack, atomic rename)
    whenever dirty and restored on start — the durability role etcd's raft
    log plays in the reference (ref: transports/etcd.rs). Leased keys are
    deliberately NOT persisted: they are liveness claims whose owners must
    re-assert them (clients re-put leased keys on reconnect, see
    :class:`StoreClient`), exactly like etcd leases dying with the cluster.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = DEFAULT_PORT,
                 persist_path: Optional[str] = None,
                 persist_interval_s: float = 1.0):
        self.host = host
        self.port = port
        self.persist_path = persist_path
        self.persist_interval_s = persist_interval_s
        self._kv: Dict[str, _KvEntry] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watches: Dict[int, _Watch] = {}
        self._subs: Dict[int, _Watch] = {}  # pub/sub subjects (no storage)
        self._queues: Dict[str, "_WorkQueue"] = {}
        self._locks: Dict[str, Tuple[int, int]] = {}  # name -> (lease_id, watch count)
        self._revision = 0
        # identifies this server process: a re-watching client presents the
        # incarnation it was watching; a mismatch (store restarted) forces a
        # full snapshot resync instead of a bogus revision catch-up
        self.incarnation = uuid.uuid4().hex
        # recent (rev, event, key, value) for revision catch-up on re-watch
        self._history: deque = deque(maxlen=_MAX_EVENT_HISTORY)
        # time-seeded so a restarted store never re-issues watch/lease ids a
        # client still holds from the previous incarnation (a stale
        # WatchStream.cancel would otherwise unwatch a stranger's fresh id)
        self._ids = itertools.count(int(time.time()) % (1 << 30) << 16)
        self._server: Optional[asyncio.AbstractServer] = None
        self._expiry_task: Optional[asyncio.Task] = None
        self._persist_task: Optional[asyncio.Task] = None
        self._dirty = False
        self._conn_writers: set = set()

    # -- lifecycle --

    async def start(self) -> None:
        if self.persist_path:
            self._restore()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._expiry_task = asyncio.create_task(self._expire_loop())
        if self.persist_path:
            self._persist_task = asyncio.create_task(self._persist_loop())
        log.info("store listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._persist_task:
            self._persist_task.cancel()
            self._persist_task = None
        if self.persist_path and self._dirty:
            self._persist()
        for writer in list(self._conn_writers):
            writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    # -- durability --

    def _restore(self) -> None:
        import os

        if not os.path.exists(self.persist_path):
            return
        # The snapshot is a stream of msgpack frames: a header record, one
        # record per kv pair / queue, and a trailing {"eof": True}. A crash
        # mid-write leaves a truncated or corrupt trailing frame — restore
        # keeps everything up to the last good record instead of failing
        # startup. (The legacy single-blob format is still readable.)
        records: List[dict] = []
        clean = False
        try:
            with open(self.persist_path, "rb") as f:
                unpacker = msgpack.Unpacker(f, raw=False)
                try:
                    for rec in unpacker:
                        if not isinstance(rec, dict):
                            log.warning("store snapshot: non-dict frame — "
                                        "stopping at last good record")
                            break
                        if rec.get("eof"):
                            clean = True
                            break
                        records.append(rec)
                except Exception as exc:
                    log.warning(
                        "store snapshot truncated/corrupt after %d records "
                        "(%s) — continuing from last good record",
                        len(records), exc,
                    )
        except Exception:
            log.exception("store restore failed — starting empty")
            return
        if not records:
            return
        try:
            first = records[0]
            if "header" in first:
                revision = int(first["header"].get("revision", 0))
                kv: Dict[str, _KvEntry] = {}
                queues: Dict[str, _WorkQueue] = {}
                for rec in records[1:]:
                    if "kv" in rec:
                        key, value = rec["kv"]
                        kv[key] = _KvEntry(value, 0, revision, revision)
                    elif "q" in rec:
                        name, items = rec["q"]
                        q = _WorkQueue()
                        q.items.extend(bytes(i) for i in items)
                        queues[name] = q
            else:
                # legacy format: one blob {revision, kv, queues}
                revision = int(first.get("revision", 0))
                kv = {
                    key: _KvEntry(value, 0, revision, revision)
                    for key, value in first.get("kv", [])
                }
                queues = {}
                for name, items in first.get("queues", {}).items():
                    q = _WorkQueue()
                    q.items.extend(bytes(i) for i in items)
                    queues[name] = q
                clean = True
            self._revision = revision
            self._kv = kv
            self._queues = queues
            log.info(
                "restored %d keys, %d queues at revision %d from %s%s",
                len(self._kv), len(self._queues), self._revision,
                self.persist_path, "" if clean else " (truncated tail)",
            )
        except Exception:
            log.exception("store restore failed — starting empty")
            self._revision = 0
            self._kv = {}
            self._queues = {}

    def _persist(self) -> None:
        import os
        import tempfile

        try:
            packer = msgpack.Packer(use_bin_type=True)
            d = os.path.dirname(os.path.abspath(self.persist_path))
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(packer.pack(
                    {"header": {"revision": self._revision, "format": 2}}
                ))
                # leased keys are liveness claims — never persisted
                for k, e in sorted(self._kv.items()):
                    if e.lease_id == 0:
                        f.write(packer.pack({"kv": [k, e.value]}))
                for name, q in self._queues.items():
                    if q.items:
                        f.write(packer.pack({"q": [name, q.items]}))
                f.write(packer.pack({"eof": True}))
            os.replace(tmp, self.persist_path)
            self._dirty = False
        except Exception:
            log.exception("store persist failed")

    async def _persist_loop(self) -> None:
        while True:
            await asyncio.sleep(self.persist_interval_s)
            if self._dirty:
                self._persist()

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    # -- lease expiry --

    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            dead = [l for l in self._leases.values() if l.deadline < now]
            for lease in dead:
                log.info("lease %d expired (ttl %.1fs)", lease.lease_id, lease.ttl_s)
                self._revoke(lease.lease_id)

    def _revoke(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            self._delete_key(key)
        for name, (owner, _) in list(self._locks.items()):
            if owner == lease_id:
                del self._locks[name]

    # -- kv ops (single-threaded within the event loop => atomic) --

    def _push_event(self, registry: Dict[int, _Watch], watch: _Watch,
                    frame: dict) -> bool:
        """Write an event frame to a watcher with backpressure protection.

        Fan-out happens in sync code (no ``drain()``), so a slow consumer
        would otherwise accumulate unbounded write buffers under event storms
        (the KV-events subject is the hottest, ref: kv_router.rs:60). Policy:
        when the connection's socket buffer exceeds the limit, unregister the
        watch being written (under a storm that is the hot subject) and send
        it a final small ``dropped`` event — the NATS slow-consumer contract.
        The connection stays open: it also carries RPCs and the primary-lease
        keepalive, so closing it would turn one slow subscription into a
        spurious whole-worker death. Clients resubscribe on ``dropped``.
        """
        writer = watch.writer
        if writer.is_closing():
            registry.pop(watch.watch_id, None)
            return False
        if writer.transport.get_write_buffer_size() > _MAX_SUB_BUFFER:
            log.warning(
                "watch %d too slow (%d bytes buffered) — dropping watch",
                watch.watch_id, writer.transport.get_write_buffer_size(),
            )
            registry.pop(watch.watch_id, None)
            try:
                write_frame(writer, {"seq": None, "watch_id": watch.watch_id,
                                     "event": "dropped", "key": watch.prefix,
                                     "value": None, "rev": 0})
            except Exception:
                pass
            return False
        try:
            write_frame(writer, frame)
            return True
        except Exception:
            registry.pop(watch.watch_id, None)
            return False

    def _notify(self, event: str, key: str, value: Optional[bytes], rev: int) -> None:
        self._history.append((rev, event, key, value))
        for watch in list(self._watches.values()):
            if key.startswith(watch.prefix):
                self._push_event(
                    self._watches, watch,
                    {
                        "seq": None,
                        "watch_id": watch.watch_id,
                        "event": event,
                        "key": key,
                        "value": value,
                        "rev": rev,
                    },
                )

    def _put(self, key: str, value: bytes, lease_id: int) -> int:
        # validate the lease BEFORE mutating: a put under an expired/unknown
        # lease must have no side effects (no orphan keys, no notifications)
        lease = None
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise KeyError(f"unknown lease {lease_id}")
        self._revision += 1
        prev = self._kv.get(key)
        create_rev = prev.create_rev if prev else self._revision
        if prev and prev.lease_id and prev.lease_id != lease_id:
            old = self._leases.get(prev.lease_id)
            if old:
                old.keys.discard(key)
        self._kv[key] = _KvEntry(value, lease_id, create_rev, self._revision)
        if lease is not None:
            lease.keys.add(key)
        # dirty when the persisted set changes: an unleased write, or a
        # leased write shadowing a previously-persisted unleased key
        if lease_id == 0 or (prev is not None and prev.lease_id == 0):
            self._dirty = True
        self._notify("put", key, value, self._revision)
        return self._revision

    def _delete_key(self, key: str) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        self._revision += 1
        if entry.lease_id:
            lease = self._leases.get(entry.lease_id)
            if lease:
                lease.keys.discard(key)
        else:
            self._dirty = True
        self._notify("delete", key, None, self._revision)
        return True

    # -- request dispatch --

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn_watches: List[int] = []
        conn_leases: List[int] = []
        self._conn_writers.add(writer)
        try:
            while True:
                msg = await read_frame(reader)
                if msg is None:
                    break
                resp = self._dispatch(msg, writer, conn_watches, conn_leases)
                if resp is not None:
                    write_frame(writer, resp)
                    await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        except Exception:  # malformed frame / codec garbage: drop the conn
            log.warning("dropping store connection after bad frame", exc_info=True)
        finally:
            for wid in conn_watches:
                self._watches.pop(wid, None)
                self._subs.pop(wid, None)
            # leases owned by this connection survive until TTL expiry — a
            # reconnecting client can re-attach via keepalive (etcd semantics)
            self._conn_writers.discard(writer)
            writer.close()

    def _dispatch(
        self,
        msg: dict,
        writer: asyncio.StreamWriter,
        conn_watches: List[int],
        conn_leases: List[int],
    ) -> Optional[dict]:
        op = msg.get("op")
        seq = msg.get("seq")
        try:
            if op == "put":
                rev = self._put(msg["key"], msg["value"], msg.get("lease", 0))
                return {"seq": seq, "ok": True, "rev": rev}
            if op == "create":  # atomic create: fail if key exists (kv_create)
                if msg["key"] in self._kv:
                    return {"seq": seq, "ok": False, "error": "exists"}
                rev = self._put(msg["key"], msg["value"], msg.get("lease", 0))
                return {"seq": seq, "ok": True, "rev": rev}
            if op == "cas":
                entry = self._kv.get(msg["key"])
                expect = msg.get("expect")  # None = must not exist
                actual = entry.value if entry else None
                if actual != expect:
                    return {"seq": seq, "ok": False, "error": "conflict",
                            "value": actual}
                rev = self._put(msg["key"], msg["value"], msg.get("lease", 0))
                return {"seq": seq, "ok": True, "rev": rev}
            if op == "get":
                entry = self._kv.get(msg["key"])
                if entry is None:
                    return {"seq": seq, "ok": True, "kvs": []}
                return {
                    "seq": seq,
                    "ok": True,
                    "kvs": [[msg["key"], entry.value, entry.lease_id, entry.mod_rev]],
                }
            if op == "get_prefix":
                prefix = msg["prefix"]
                kvs = [
                    [k, e.value, e.lease_id, e.mod_rev]
                    for k, e in sorted(self._kv.items())
                    if k.startswith(prefix)
                ]
                return {"seq": seq, "ok": True, "kvs": kvs, "rev": self._revision}
            if op == "delete":
                existed = self._delete_key(msg["key"])
                return {"seq": seq, "ok": True, "deleted": existed}
            if op == "delete_prefix":
                keys = [k for k in self._kv if k.startswith(msg["prefix"])]
                for k in keys:
                    self._delete_key(k)
                return {"seq": seq, "ok": True, "deleted": len(keys)}
            if op == "lease_grant":
                lease_id = next(self._ids)
                ttl = float(msg.get("ttl", 10.0))
                self._leases[lease_id] = _Lease(
                    lease_id, ttl, time.monotonic() + ttl
                )
                conn_leases.append(lease_id)
                return {"seq": seq, "ok": True, "lease": lease_id, "ttl": ttl}
            if op == "lease_keepalive":
                lease = self._leases.get(msg["lease"])
                if lease is None:
                    return {"seq": seq, "ok": False, "error": "lease_expired"}
                if lease.deadline < time.monotonic():
                    # already past the deadline — the expire loop just hasn't
                    # ticked yet. A late keepalive must NOT resurrect the
                    # lease (watchers may already be reacting to the expiry);
                    # revoke now so keepalive-vs-expiry ordering is settled
                    # here, atomically, not by loop-tick luck.
                    self._revoke(lease.lease_id)
                    return {"seq": seq, "ok": False, "error": "lease_expired"}
                lease.deadline = time.monotonic() + lease.ttl_s
                return {"seq": seq, "ok": True, "ttl": lease.ttl_s}
            if op == "lease_revoke":
                self._revoke(msg["lease"])
                return {"seq": seq, "ok": True}
            if op == "watch":
                watch_id = next(self._ids)
                prefix = msg["prefix"]
                self._watches[watch_id] = _Watch(watch_id, prefix, writer)
                conn_watches.append(watch_id)
                # revision catch-up: a re-watching client that presents the
                # revision it had seen (against the SAME server incarnation)
                # gets exactly the events it missed instead of a snapshot —
                # no reconcile diff needed on its side
                since = msg.get("since_rev")
                if (since is not None
                        and msg.get("incarnation") == self.incarnation
                        and self._covers(int(since))):
                    events = [
                        {"event": ev, "key": k, "value": v, "rev": rev}
                        for rev, ev, k, v in self._history
                        if rev > int(since) and k.startswith(prefix)
                    ]
                    return {
                        "seq": seq,
                        "ok": True,
                        "watch_id": watch_id,
                        "caught_up": True,
                        "events": events,
                        "rev": self._revision,
                        "incarnation": self.incarnation,
                    }
                # current state snapshot so the watcher can't miss anything
                kvs = [
                    [k, e.value, e.lease_id, e.mod_rev]
                    for k, e in sorted(self._kv.items())
                    if k.startswith(prefix)
                ]
                return {
                    "seq": seq,
                    "ok": True,
                    "watch_id": watch_id,
                    "kvs": kvs,
                    "rev": self._revision,
                    "incarnation": self.incarnation,
                }
            if op == "unwatch":
                self._watches.pop(msg["watch_id"], None)
                return {"seq": seq, "ok": True}
            if op == "lock":
                name, lease_id = msg["name"], msg["lease"]
                if lease_id not in self._leases:
                    return {"seq": seq, "ok": False, "error": "lease_expired"}
                holder = self._locks.get(name)
                if holder is None or holder[0] not in self._leases:
                    self._locks[name] = (lease_id, 0)
                    return {"seq": seq, "ok": True, "acquired": True}
                return {"seq": seq, "ok": True, "acquired": holder[0] == lease_id}
            if op == "unlock":
                holder = self._locks.get(msg["name"])
                if holder and holder[0] == msg["lease"]:
                    del self._locks[msg["name"]]
                return {"seq": seq, "ok": True}
            if op == "subscribe":
                sub_id = next(self._ids)
                self._subs[sub_id] = _Watch(sub_id, msg["subject"], writer)
                conn_watches.append(sub_id)  # cleaned with watches on disconnect
                return {"seq": seq, "ok": True, "watch_id": sub_id}
            if op == "unsubscribe":
                self._subs.pop(msg["watch_id"], None)
                return {"seq": seq, "ok": True}
            if op == "publish":
                subject, payload = msg["subject"], msg["payload"]
                n = 0
                for sub in list(self._subs.values()):
                    if subject.startswith(sub.prefix):
                        if self._push_event(
                            self._subs, sub,
                            {"seq": None, "watch_id": sub.watch_id,
                             "event": "msg", "key": subject,
                             "value": payload, "rev": 0},
                        ):
                            n += 1
                return {"seq": seq, "ok": True, "delivered": n}
            if op == "q_push":
                q = self._queues.setdefault(msg["queue"], _WorkQueue())
                depth = q.push(msg["payload"])
                self._dirty = True
                return {"seq": seq, "ok": True, "depth": depth}
            if op == "q_pop":
                q = self._queues.setdefault(msg["queue"], _WorkQueue())
                item = q.pop_nowait()
                if item is not None:
                    self._dirty = True
                    return {"seq": seq, "ok": True, "payload": item}
                self._q_pop_async(q, msg, writer)
                return None  # response written when an item arrives / timeout
            if op == "q_len":
                q = self._queues.get(msg["queue"])
                return {"seq": seq, "ok": True,
                        "depth": len(q.items) if q else 0}
            if op == "ping":
                return {"seq": seq, "ok": True, "rev": self._revision,
                        "incarnation": self.incarnation}
            return {"seq": seq, "ok": False, "error": f"unknown op {op!r}"}
        except Exception as exc:  # noqa: BLE001 — report, don't kill the conn
            log.exception("store op %s failed", op)
            return {"seq": seq, "ok": False, "error": str(exc)}

    def _covers(self, since_rev: int) -> bool:
        """True when the retained event history holds every revision after
        ``since_rev`` (so a catch-up replay misses nothing)."""
        if since_rev >= self._revision:
            return True
        return bool(self._history) and self._history[0][0] <= since_rev + 1

    def _q_pop_async(
        self, q: "_WorkQueue", msg: dict, writer: asyncio.StreamWriter
    ) -> None:
        """Blocking pull: respond when an item arrives or the timeout fires.
        If the consumer vanished by delivery time, the item is re-queued
        (at-least-once, the JetStream work-queue contract)."""
        seq = msg.get("seq")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        q.waiters.append(fut)

        def _deliver(f: asyncio.Future) -> None:
            if f.cancelled():
                payload = None
            else:
                payload = f.result()
                self._dirty = True
            if writer.is_closing():
                if payload is not None:
                    q.push(payload)
                return
            try:
                write_frame(writer, {"seq": seq, "ok": True, "payload": payload})
            except Exception:
                if payload is not None:
                    q.push(payload)

        fut.add_done_callback(_deliver)
        timeout = float(msg.get("timeout", 30.0))

        def _expire() -> None:
            if not fut.done():
                fut.cancel()
                try:
                    q.waiters.remove(fut)
                except ValueError:
                    pass

        asyncio.get_running_loop().call_later(timeout, _expire)


# ------------------------------- client ----------------------------------


class StoreError(RuntimeError):
    pass


class LeaseExpired(StoreError):
    pass


class StoreClient:
    """Async client for :class:`StoreServer`.

    Holds one multiplexed connection; a background reader routes responses by
    ``seq`` and fans watch events out to per-watch queues. A *primary lease*
    with automatic keepalive mirrors the reference runtime's liveness contract:
    if the primary lease cannot be kept alive, ``on_lease_lost`` fires (the
    runtime uses this to trigger shutdown, ref: etcd.rs:89-95).
    """

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_queues: Dict[int, asyncio.Queue] = {}
        # events that raced ahead of watch registration (the server can push
        # events for a fresh watch_id before the watch/subscribe response is
        # processed by the caller); drained into the queue on registration
        self._orphan_events: Dict[int, List[dict]] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self.primary_lease: int = 0
        self.on_lease_lost: Optional[Callable[[], None]] = None
        self._closed = False
        self._lease_ttl_s: float = 10.0
        # keys this client holds under its primary lease, re-asserted after
        # a reconnect (a restarted store forgot them; the reference's etcd
        # survives via raft — here the client replays its own claims)
        self._leased_keys: Dict[str, bytes] = {}
        self._recover_task: Optional[asyncio.Task] = None
        # how long reconnect attempts may run before declaring lease loss
        self.recover_timeout_s: float = 30.0
        # reconnect pacing: jittered exponential backoff between attempts
        self.reconnect_base_s: float = 0.25
        self.reconnect_cap_s: float = 5.0
        self._reconnect_rng = random.Random()
        self.num_recoveries = 0
        # failed RPC attempts (injected faults + dead-connection calls):
        # the store-seam evidence the replay fault-attribution check reads
        self.num_call_errors = 0

    @staticmethod
    async def connect(
        addr: str, *, lease_ttl_s: float = 10.0, retries: int = 40,
        retry_delay_s: float = 0.25, recover_timeout_s: float = 30.0,
        reconnect_base_s: float = 0.25, reconnect_cap_s: float = 5.0,
    ) -> "StoreClient":
        host, port = addr.rsplit(":", 1)
        client = StoreClient(host, int(port))
        client.recover_timeout_s = recover_timeout_s
        client.reconnect_base_s = reconnect_base_s
        client.reconnect_cap_s = reconnect_cap_s
        last: Optional[Exception] = None
        for _ in range(retries):
            try:
                await client._open()
                break
            except OSError as exc:
                last = exc
                await asyncio.sleep(retry_delay_s)
        else:
            raise StoreError(f"cannot connect to store at {addr}: {last}")
        client._lease_ttl_s = lease_ttl_s
        client.primary_lease = await client.lease_grant(lease_ttl_s)
        client._keepalive_task = asyncio.create_task(
            client._keepalive_loop(lease_ttl_s)
        )
        return client

    async def _open(self) -> None:
        fault = await faults.maybe_delay(
            faults.active("store.connect", f"{self.host}:{self.port}")
        )
        if fault is not None and fault.kind in (faults.DROP, faults.REJECT):
            # OSError so both the initial connect-retry loop and the
            # recovery loop treat it exactly like a refused dial
            raise OSError(f"injected store.connect fault "
                          f"({self.host}:{self.port})")
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        self._reader_task = asyncio.create_task(self._read_loop())

    async def close(self) -> None:
        self._closed = True
        if self._keepalive_task:
            self._keepalive_task.cancel()
        # revoke while the reader is still alive so the response resolves
        if self.primary_lease and self._writer and not self._writer.is_closing():
            try:
                await asyncio.wait_for(
                    self._call({"op": "lease_revoke", "lease": self.primary_lease}),
                    timeout=2.0,
                )
            except Exception:
                pass
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()

    async def _read_loop(self) -> None:
        assert self._reader is not None
        while True:
            msg = await read_frame(self._reader)
            if msg is None:
                # mark the connection dead FIRST so a racing _call() raises
                # instead of registering a future nothing will ever resolve
                if self._writer is not None:
                    self._writer.close()
                self._fail_pending()
                # watchers see "dropped" (not a silent end): consumers
                # resubscribe on dropped, retrying through the reconnect
                # window
                for wid, q in list(self._watch_queues.items()):
                    q.put_nowait(
                        None if self._closed
                        else {"watch_id": wid, "event": "dropped",
                              "key": "", "value": None, "rev": 0}
                    )
                if not self._closed:
                    self._start_recovery()
                return
            seq = msg.get("seq")
            if seq is None:
                wid = msg.get("watch_id")
                q = self._watch_queues.get(wid)
                if q is not None:
                    q.put_nowait(msg)
                elif wid is not None:
                    # bounded: an id that is never claimed (caller died between
                    # the watch RPC and claiming) must not leak memory — past
                    # the cap the buffer collapses to a single 'dropped'
                    # tombstone so a late claimer knows it has a gap and must
                    # resynchronise, instead of silently missing events
                    buf = self._orphan_events.setdefault(wid, [])
                    if buf and buf[0].get("event") == "dropped":
                        continue
                    buf.append(msg)
                    if len(buf) > _MAX_ORPHAN_EVENTS:
                        self._orphan_events[wid] = [
                            {"watch_id": wid, "event": "dropped"}
                        ]
            else:
                fut = self._pending.pop(seq, None)
                if fut and not fut.done():
                    fut.set_result(msg)

    async def _call(self, msg: dict) -> dict:
        fault = await faults.maybe_delay(
            faults.active("store.call", msg.get("op") or "")
        )
        if fault is not None and fault.kind in (faults.DROP, faults.REJECT):
            self.num_call_errors += 1
            raise StoreError(f"injected store fault on {msg.get('op')!r}")
        if self._writer is None or self._writer.is_closing():
            self.num_call_errors += 1
            raise StoreError("store client not connected")
        seq = next(self._seq)
        msg["seq"] = seq
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        write_frame(self._writer, msg)
        await self._writer.drain()
        return await fut

    async def _keepalive_loop(self, ttl_s: float) -> None:
        period = max(ttl_s / 3.0, 0.2)
        while not self._closed:
            await asyncio.sleep(period)
            try:
                resp = await asyncio.wait_for(
                    self._call(
                        {"op": "lease_keepalive", "lease": self.primary_lease}
                    ),
                    timeout=ttl_s,
                )
                if not resp.get("ok"):
                    raise LeaseExpired("primary lease expired")
            except Exception:
                if self._closed:
                    return
                # lease unknown / connection gone: try recovery (a restarted
                # store grants a fresh lease and we re-assert our keys)
                # before declaring the worker dead
                log.warning("primary lease keepalive failed — recovering")
                self._start_recovery()
                return

    async def kick_keepalive(self) -> bool:
        """Send one primary-lease keepalive now, outside the periodic loop.

        Chaos-replay hook: a ``store.call``/``lease_keepalive`` fault rule
        gates an op the replay clock does not control — the periodic loop's
        phase is set at client spawn, so whether a finite-``times`` rule
        fires within a replay window depends on wall-clock luck. Kicking at
        wave install pins each firing to a deterministic point. A failed
        kick takes the same recovery path as a failed periodic tick.
        """
        try:
            resp = await asyncio.wait_for(
                self._call(
                    {"op": "lease_keepalive", "lease": self.primary_lease}
                ),
                timeout=self._lease_ttl_s,
            )
            if not resp.get("ok"):
                raise LeaseExpired("primary lease expired")
            return True
        except Exception:
            if not self._closed:
                log.warning("kicked keepalive failed — recovering")
                self._start_recovery()
            return False

    # -- reconnect / lease recovery --

    def _fail_pending(self) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(StoreError("store connection closed"))
        self._pending.clear()

    def _start_recovery(self) -> None:
        if self._closed or self._recover_task is not None:
            return
        self._recover_task = asyncio.create_task(self._recover())

    async def _recover(self) -> None:
        """Reconnect, re-grant the primary lease, re-assert leased keys.

        Key identity is preserved: instance records carry their original
        instance_id in the VALUE, so watchers see the same worker come back
        (a put on the same key), not a new one. Gives up after
        ``recover_timeout_s`` and fires ``on_lease_lost``.
        """
        deadline = time.monotonic() + self.recover_timeout_s
        attempt = 0
        try:
            while not self._closed:
                try:
                    if self._keepalive_task:
                        self._keepalive_task.cancel()
                        self._keepalive_task = None
                    if self._reader_task:
                        self._reader_task.cancel()
                    if self._writer is not None:
                        self._writer.close()
                    # in-flight RPCs from the keepalive-triggered path (the
                    # reader may still have been alive) must fail, not hang
                    self._fail_pending()
                    await self._open()
                    self.primary_lease = await self.lease_grant(
                        self._lease_ttl_s
                    )
                    for key, value in list(self._leased_keys.items()):
                        await self.put(key, value, lease=self.primary_lease)
                    self._keepalive_task = asyncio.create_task(
                        self._keepalive_loop(self._lease_ttl_s)
                    )
                    self.num_recoveries += 1
                    log.info(
                        "store connection recovered (lease %d, %d keys "
                        "re-asserted)", self.primary_lease,
                        len(self._leased_keys),
                    )
                    return
                except Exception as exc:
                    if time.monotonic() > deadline:
                        log.error(
                            "store recovery failed for %.0fs (%s) — "
                            "signalling lease loss",
                            self.recover_timeout_s, exc,
                        )
                        if self.on_lease_lost:
                            self.on_lease_lost()
                        return
                    # jittered exponential backoff: avoids a thundering herd
                    # of reconnect dials when a whole cluster loses the store
                    delay = min(
                        self.reconnect_base_s * (2 ** attempt),
                        self.reconnect_cap_s,
                    ) * (0.5 + 0.5 * self._reconnect_rng.random())
                    attempt += 1
                    await asyncio.sleep(delay)
        finally:
            self._recover_task = None

    # -- public kv api --

    def _track_leased(self, key: str, value: bytes, lease: int) -> None:
        if lease and lease == self.primary_lease:
            self._leased_keys[key] = value

    async def put(self, key: str, value: bytes, lease: int = 0) -> int:
        resp = await self._call(
            {"op": "put", "key": key, "value": value, "lease": lease}
        )
        if not resp["ok"]:
            raise StoreError(resp.get("error", "put failed"))
        self._track_leased(key, value, lease)
        return resp["rev"]

    async def create(self, key: str, value: bytes, lease: int = 0) -> bool:
        """Atomic create; False if the key already exists (ref: kv_create)."""
        resp = await self._call(
            {"op": "create", "key": key, "value": value, "lease": lease}
        )
        if resp["ok"]:
            self._track_leased(key, value, lease)
        return bool(resp["ok"])

    async def cas(
        self, key: str, expect: Optional[bytes], value: bytes, lease: int = 0
    ) -> bool:
        resp = await self._call(
            {"op": "cas", "key": key, "expect": expect, "value": value,
             "lease": lease}
        )
        if resp["ok"]:
            self._track_leased(key, value, lease)
        return bool(resp["ok"])

    async def get(self, key: str) -> Optional[bytes]:
        resp = await self._call({"op": "get", "key": key})
        kvs = resp.get("kvs", [])
        return kvs[0][1] if kvs else None

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        resp = await self._call({"op": "get_prefix", "prefix": prefix})
        return [(k, v) for k, v, _lease, _rev in resp.get("kvs", [])]

    async def delete(self, key: str) -> bool:
        # untrack BEFORE the RPC: if the store is down the delete raises, and
        # a later lease recovery must not resurrect a key we meant to remove
        self._leased_keys.pop(key, None)
        resp = await self._call({"op": "delete", "key": key})
        return bool(resp.get("deleted"))

    async def delete_prefix(self, prefix: str) -> int:
        for key in [k for k in self._leased_keys if k.startswith(prefix)]:
            del self._leased_keys[key]
        resp = await self._call({"op": "delete_prefix", "prefix": prefix})
        return int(resp.get("deleted", 0))

    async def lease_grant(self, ttl_s: float) -> int:
        resp = await self._call({"op": "lease_grant", "ttl": ttl_s})
        if not resp["ok"]:
            raise StoreError(resp.get("error", "lease_grant failed"))
        return resp["lease"]

    async def lease_revoke(self, lease: int) -> None:
        await self._call({"op": "lease_revoke", "lease": lease})

    async def lock(self, name: str, lease: Optional[int] = None) -> bool:
        resp = await self._call(
            {"op": "lock", "name": name, "lease": lease or self.primary_lease}
        )
        return bool(resp.get("acquired"))

    async def unlock(self, name: str, lease: Optional[int] = None) -> None:
        await self._call(
            {"op": "unlock", "name": name, "lease": lease or self.primary_lease}
        )

    async def _watch_raw(
        self, prefix: str, *, since_rev: Optional[int] = None,
        incarnation: Optional[str] = None,
    ) -> Tuple[dict, "WatchStream"]:
        """Low-level watch subscribe; returns the full server response (which
        carries either a ``kvs`` snapshot or a ``caught_up`` event delta) plus
        the claimed event stream."""
        fault = await faults.maybe_delay(faults.active("store.watch", prefix))
        if fault is not None and fault.kind in (faults.DROP, faults.REJECT):
            raise StoreError(f"injected store.watch fault on {prefix!r}")
        msg: dict = {"op": "watch", "prefix": prefix}
        if since_rev is not None:
            msg["since_rev"] = since_rev
            msg["incarnation"] = incarnation
        resp = await self._call(msg)
        if not resp["ok"]:
            raise StoreError(resp.get("error", "watch failed"))
        return resp, WatchStream(
            self, resp["watch_id"], self._claim_watch_queue(resp["watch_id"])
        )

    async def watch_prefix(
        self, prefix: str
    ) -> Tuple[List[Tuple[str, bytes]], "WatchStream"]:
        """Subscribe to a prefix; returns (current snapshot, event stream)."""
        resp, stream = await self._watch_raw(prefix)
        snapshot = [(k, v) for k, v, _l, _r in resp.get("kvs", [])]
        return snapshot, stream

    async def watch_prefix_resilient(
        self, prefix: str, *, grace_s: float = 0.0,
        rewatch_delay_s: float = 0.25,
    ) -> Tuple[List[Tuple[str, bytes]], "ResilientWatchStream"]:
        """Watch a prefix across store outages (stale-while-revalidate).

        Like :meth:`watch_prefix`, but the returned stream survives dropped
        watches and store restarts: it re-subscribes on its own, replays the
        missed event delta when the server can still cover our revision
        (same incarnation, history not overrun), and otherwise reconciles
        against a fresh snapshot — emitting synthetic puts for new/changed
        keys and synthetic deletes for keys that vanished. Deletes arising
        from a reconcile are deferred ``grace_s`` seconds and re-verified
        with a direct get, so keys whose owners are *also* mid-recovery
        (their lease re-put races ours) aren't flapped out of the last-known
        snapshot. During an outage consumers simply see no events and keep
        serving ``stream.state`` — the last-known view."""
        resp, inner = await self._watch_raw(prefix)
        snapshot = [(k, v) for k, v, _l, _r in resp.get("kvs", [])]
        stream = ResilientWatchStream(
            self, prefix, inner, snapshot,
            last_rev=resp.get("rev", 0),
            incarnation=resp.get("incarnation"),
            grace_s=grace_s, rewatch_delay_s=rewatch_delay_s,
        )
        return snapshot, stream

    def _claim_watch_queue(self, watch_id: int) -> asyncio.Queue:
        """Register the event queue, draining any events that arrived between
        the server creating the watch and the caller claiming it."""
        queue: asyncio.Queue = asyncio.Queue()
        for event in self._orphan_events.pop(watch_id, []):
            queue.put_nowait(event)
        self._watch_queues[watch_id] = queue
        return queue

    # -- pub/sub (NATS-subject role) --

    async def publish(self, subject: str, payload: bytes) -> int:
        resp = await self._call(
            {"op": "publish", "subject": subject, "payload": payload}
        )
        return int(resp.get("delivered", 0))

    async def subscribe(self, subject_prefix: str) -> "WatchStream":
        """Subscribe to a subject prefix; events have ``event == 'msg'``."""
        resp = await self._call({"op": "subscribe", "subject": subject_prefix})
        if not resp["ok"]:
            raise StoreError(resp.get("error", "subscribe failed"))
        watch_id = resp["watch_id"]
        return WatchStream(
            self, watch_id, self._claim_watch_queue(watch_id), kind="subscribe"
        )

    # -- work queues (JetStream pull-consumer role, ref: nats.rs:426) --

    async def q_push(self, queue: str, payload: bytes) -> int:
        resp = await self._call({"op": "q_push", "queue": queue, "payload": payload})
        return int(resp.get("depth", 0))

    async def q_pop(self, queue: str, timeout_s: float = 30.0) -> Optional[bytes]:
        resp = await self._call(
            {"op": "q_pop", "queue": queue, "timeout": timeout_s}
        )
        return resp.get("payload")

    async def q_len(self, queue: str) -> int:
        resp = await self._call({"op": "q_len", "queue": queue})
        return int(resp.get("depth", 0))

    async def wait_for_key_count(
        self, prefix: str, count: int, timeout_s: float = 60.0
    ) -> List[Tuple[str, bytes]]:
        """Block until >= ``count`` keys exist under ``prefix``
        (ref: leader_worker_barrier.rs:24)."""
        snapshot, stream = await self.watch_prefix(prefix)
        try:
            seen = dict(snapshot)
            deadline = time.monotonic() + timeout_s
            while len(seen) < count:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"barrier timeout: {len(seen)}/{count} under {prefix!r}"
                    )
                event = await asyncio.wait_for(stream.next(), timeout=remaining)
                if event is None:
                    raise StoreError("store connection lost during barrier")
                if event["event"] == "dropped":
                    # watch shed under backpressure — resubscribe and resync
                    await stream.cancel()
                    snapshot, stream = await self.watch_prefix(prefix)
                    seen = dict(snapshot)
                elif event["event"] == "put":
                    seen[event["key"]] = event["value"]
                else:
                    seen.pop(event["key"], None)
            return sorted(seen.items())
        finally:
            await stream.cancel()


class WatchStream:
    """Stream of {'event': 'put'|'delete', 'key', 'value', 'rev'} dicts."""

    def __init__(
        self,
        client: StoreClient,
        watch_id: int,
        queue: asyncio.Queue,
        kind: str = "watch",
    ):
        self._client = client
        self.watch_id = watch_id
        self._queue = queue
        self._kind = kind

    async def next(self) -> Optional[dict]:
        return await self._queue.get()

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            event = await self._queue.get()
            if event is None:
                return
            yield event

    async def cancel(self) -> None:
        self._client._watch_queues.pop(self.watch_id, None)
        op = "unwatch" if self._kind == "watch" else "unsubscribe"
        try:
            await self._client._call({"op": op, "watch_id": self.watch_id})
        except StoreError:
            pass
        # events in flight between pop and the unwatch ack land in the orphan
        # buffer; discard them so cancelled watches don't leak memory
        self._client._orphan_events.pop(self.watch_id, None)


class ResilientWatchStream:
    """A prefix watch that outlives dropped watches and store restarts.

    Same ``next()`` contract as :class:`WatchStream` (None == client closed
    for good), but ``'dropped'`` never reaches the consumer: the stream
    re-subscribes, replays the missed delta when the server still covers our
    revision, and otherwise reconciles a fresh snapshot into synthetic
    put/delete events. ``state`` is the last-known key->value view — safe to
    read at any time, including mid-outage (stale-while-revalidate).
    """

    def __init__(
        self,
        client: StoreClient,
        prefix: str,
        inner: WatchStream,
        snapshot: List[Tuple[str, bytes]],
        *,
        last_rev: int = 0,
        incarnation: Optional[str] = None,
        grace_s: float = 0.0,
        rewatch_delay_s: float = 0.25,
    ):
        self._client = client
        self.prefix = prefix
        self._inner = inner
        self.state: Dict[str, bytes] = dict(snapshot)
        self.last_rev = last_rev
        self.incarnation = incarnation
        self.grace_s = grace_s
        self.rewatch_delay_s = rewatch_delay_s
        self._out: asyncio.Queue = asyncio.Queue()
        self._pending_stale: Dict[str, asyncio.Task] = {}
        self.num_resyncs = 0
        self.num_catchups = 0
        self._driver = asyncio.create_task(self._run())

    def _track(self, event: dict) -> None:
        key = event.get("key")
        if event["event"] == "put":
            self.state[key] = event.get("value")
            self._cancel_stale(key)
        elif event["event"] == "delete":
            self.state.pop(key, None)
            self._cancel_stale(key)
        self.last_rev = max(self.last_rev, event.get("rev") or 0)

    def _cancel_stale(self, key: str) -> None:
        task = self._pending_stale.pop(key, None)
        if task is not None:
            task.cancel()

    async def _run(self) -> None:
        while True:
            event = await self._inner.next()
            if event is None:
                self._out.put_nowait(None)
                return
            if event["event"] == "dropped":
                if not await self._resync():
                    self._out.put_nowait(None)
                    return
                continue
            self._track(event)
            self._out.put_nowait(event)

    async def _resync(self) -> bool:
        """Re-subscribe after a drop; replay the delta or reconcile a
        snapshot. Returns False only when the client itself is closed."""
        # the old watch belongs to a dead (or shed) server registration;
        # drop the local queue and best-effort unwatch
        try:
            await self._inner.cancel()
        except Exception:
            pass
        while True:
            if self._client._closed:
                return False
            try:
                resp, inner = await self._client._watch_raw(
                    self.prefix, since_rev=self.last_rev,
                    incarnation=self.incarnation,
                )
                break
            except (StoreError, OSError):
                # store still down (or mid-recovery) — the consumer keeps
                # serving ``state`` while we retry
                await asyncio.sleep(self.rewatch_delay_s)
        self._inner = inner
        self.num_resyncs += 1
        self.incarnation = resp.get("incarnation")
        if resp.get("caught_up"):
            self.num_catchups += 1
            for event in resp.get("events", []):
                self._track(event)
                self._out.put_nowait(event)
            self.last_rev = max(self.last_rev, resp.get("rev") or 0)
            return True
        # snapshot reconcile: diff last-known state against the fresh view
        live = {k: v for k, v, _l, _r in resp.get("kvs", [])}
        rev = resp.get("rev") or 0
        for key, value in live.items():
            if self.state.get(key) != value:
                event = {"event": "put", "key": key, "value": value,
                         "rev": rev, "resync": True}
                self._track(event)
                self._out.put_nowait(event)
        for key in [k for k in self.state if k not in live]:
            if self.grace_s <= 0:
                event = {"event": "delete", "key": key, "value": None,
                         "rev": rev, "resync": True}
                self._track(event)
                self._out.put_nowait(event)
            elif key not in self._pending_stale:
                # the key's owner may itself be mid-recovery (its lease
                # re-put races our re-watch) — verify before evicting
                self._pending_stale[key] = asyncio.create_task(
                    self._stale_check(key)
                )
        self.last_rev = max(self.last_rev, rev)
        return True

    async def _stale_check(self, key: str) -> None:
        try:
            await asyncio.sleep(self.grace_s)
            while True:
                try:
                    value = await self._client.get(key)
                    break
                except (StoreError, OSError):
                    if self._client._closed:
                        return
                    await asyncio.sleep(self.rewatch_delay_s)
            if value is None and key in self.state:
                event = {"event": "delete", "key": key, "value": None,
                         "rev": self.last_rev, "resync": True}
                self.state.pop(key, None)
                self._out.put_nowait(event)
            elif value is not None and self.state.get(key) != value:
                event = {"event": "put", "key": key, "value": value,
                         "rev": self.last_rev, "resync": True}
                self.state[key] = value
                self._out.put_nowait(event)
        finally:
            self._pending_stale.pop(key, None)

    async def reconcile(self) -> Dict[str, List[str]]:
        """Diff the last-known view against the store. Empty lists mean the
        stream has fully converged with the live store."""
        live = dict(await self._client.get_prefix(self.prefix))
        return {
            "missing": sorted(k for k in self.state if k not in live),
            "extra": sorted(k for k in live if k not in self.state),
            "changed": sorted(
                k for k, v in self.state.items()
                if k in live and live[k] != v
            ),
        }

    async def next(self) -> Optional[dict]:
        return await self._out.get()

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            event = await self._out.get()
            if event is None:
                return
            yield event

    async def cancel(self) -> None:
        self._driver.cancel()
        for task in list(self._pending_stale.values()):
            task.cancel()
        self._pending_stale.clear()
        try:
            await self._inner.cancel()
        except Exception:
            pass


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu discovery store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument(
        "--persist", default=None, metavar="PATH",
        help="snapshot unleased KV + work queues to PATH (msgpack, atomic "
             "rename) and restore from it on start",
    )
    args = parser.parse_args()
    server = StoreServer(args.host, args.port, persist_path=args.persist)
    asyncio.run(server.serve_forever())


if __name__ == "__main__":
    main()
