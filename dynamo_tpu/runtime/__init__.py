"""Distributed runtime substrate (host side).

Plays the role of the reference's ``lib/runtime`` crate: discovery + leases
(our own lease-KV store instead of etcd), request transport + response streams
(direct TCP with a two-part codec instead of NATS+TCP), the
Namespace/Component/Endpoint/Instance model, the AsyncEngine pipeline
abstraction with cancellation contexts, and the leader/worker barrier
(ref: lib/runtime/src/{lib.rs,component.rs,engine.rs,
utils/leader_worker_barrier.rs}).
"""

from .component import DistributedRuntime, Namespace, Component, Endpoint
from .context import Context
from .engine import AsyncEngine

__all__ = [
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "Context",
    "AsyncEngine",
]
