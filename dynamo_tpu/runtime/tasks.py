"""Hierarchical async task tracker
(ref: lib/runtime/src/utils/tasks/tracker.rs — pluggable schedulers,
OnErrorPolicy, retries, cascading cancellation, metrics).

Trackers form a tree: a child shares (or overrides) the parent's scheduler
and error policy, and cancelling a parent cascades to every descendant.
Background loops (publishers, watchers, offload pumps) spawn through a
tracker so one `cancel()`/`join()` tears down a whole subsystem and failures
are counted and policed instead of vanishing into "task exception was never
retrieved"."""

from __future__ import annotations

import asyncio
import enum
from dataclasses import dataclass, field
from typing import Awaitable, Callable, List, Optional, Set

from ..utils.logging import get_logger

log = get_logger("tasks")

# strong refs for spawn_logged: asyncio.create_task only keeps a weak ref,
# so an unreferenced task can be garbage-collected mid-flight (DT302)
_detached_tasks: Set["asyncio.Task"] = set()


def spawn_logged(coro: Awaitable, *, name: str) -> "asyncio.Task":
    """Fire-and-forget done right: the task handle is retained until the
    task settles and any non-cancellation exception hits the log instead
    of evaporating as "Task exception was never retrieved".

    For background *loops* with retry/cancellation policy use a
    :class:`TaskTracker`; this is for one-shot detached work (signal-
    triggered shutdowns, health withdraw/readvertise probes)."""
    task = asyncio.ensure_future(coro)
    if hasattr(task, "set_name"):
        task.set_name(name)
    _detached_tasks.add(task)

    def _done(t: "asyncio.Task") -> None:
        _detached_tasks.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error("detached task %s failed: %r", name, exc,
                      exc_info=exc)

    task.add_done_callback(_done)
    return task


class OnError(enum.Enum):
    """What a failed task does to its tracker (ref: tracker.rs OnErrorPolicy)."""

    LOG = "log"            # count it, log it, keep going
    SHUTDOWN = "shutdown"  # cancel the whole tracker tree
    RETRY = "retry"        # re-run with backoff up to max_retries, then LOG


@dataclass
class RetryPolicy:
    max_retries: int = 3
    backoff_s: float = 0.1
    backoff_factor: float = 2.0


class Scheduler:
    """Admission control for task starts."""

    async def acquire(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def release(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class UnlimitedScheduler(Scheduler):
    async def acquire(self) -> None:
        return

    def release(self) -> None:
        return


class SemaphoreScheduler(Scheduler):
    """At most ``n`` tracked tasks run concurrently."""

    def __init__(self, n: int):
        self._sem = asyncio.Semaphore(n)

    async def acquire(self) -> None:
        await self._sem.acquire()

    def release(self) -> None:
        self._sem.release()


@dataclass
class TrackerStats:
    spawned: int = 0
    succeeded: int = 0
    failed: int = 0
    retried: int = 0
    cancelled: int = 0


class TaskTracker:
    def __init__(
        self,
        name: str = "root",
        scheduler: Optional[Scheduler] = None,
        on_error: OnError = OnError.LOG,
        retry: Optional[RetryPolicy] = None,
        error_handler: Optional[Callable[[str, BaseException], None]] = None,
    ):
        self.name = name
        self.scheduler = scheduler or UnlimitedScheduler()
        self.on_error = on_error
        self.retry = retry or RetryPolicy()
        self.error_handler = error_handler
        self.stats = TrackerStats()
        self._tasks: Set[asyncio.Task] = set()
        self._children: List["TaskTracker"] = []
        self._cancelled = False

    # ---------------------------- tree ---------------------------------

    def child(self, name: str, **overrides) -> "TaskTracker":
        """Sub-tracker inheriting scheduler/policy unless overridden."""
        c = TaskTracker(
            name=f"{self.name}/{name}",
            scheduler=overrides.get("scheduler", self.scheduler),
            on_error=overrides.get("on_error", self.on_error),
            retry=overrides.get("retry", self.retry),
            error_handler=overrides.get("error_handler", self.error_handler),
        )
        self._children.append(c)
        return c

    # --------------------------- spawning ------------------------------

    def spawn(
        self,
        fn: Callable[[], Awaitable],
        name: Optional[str] = None,
    ) -> asyncio.Task:
        """Run ``fn`` under the tracker's scheduler and error policy.
        ``fn`` is a zero-arg coroutine *factory* so RETRY can re-invoke it."""
        if self._cancelled:
            raise RuntimeError(f"tracker {self.name} is cancelled")
        self.stats.spawned += 1
        task = asyncio.create_task(
            self._run(fn), name=name or f"{self.name}:{self.stats.spawned}"
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _run(self, fn: Callable[[], Awaitable]):
        await self.scheduler.acquire()
        try:
            attempt = 0
            while True:
                try:
                    result = await fn()
                    self.stats.succeeded += 1
                    return result
                except asyncio.CancelledError:
                    self.stats.cancelled += 1
                    raise
                except BaseException as e:
                    if (self.on_error is OnError.RETRY
                            and attempt < self.retry.max_retries):
                        self.stats.retried += 1
                        delay = (self.retry.backoff_s
                                 * self.retry.backoff_factor ** attempt)
                        attempt += 1
                        log.warning("task in %s failed (attempt %d/%d): %r",
                                    self.name, attempt,
                                    self.retry.max_retries, e)
                        await asyncio.sleep(delay)
                        continue
                    self.stats.failed += 1
                    if self.error_handler is not None:
                        try:
                            self.error_handler(self.name, e)
                        except Exception:
                            log.exception("error handler raised")
                    if self.on_error is OnError.SHUTDOWN:
                        log.error("task failure shuts down tracker %s: %r",
                                  self.name, e)
                        self.cancel()
                        return None
                    log.exception("task in %s failed", self.name)
                    return None
        finally:
            self.scheduler.release()

    # --------------------------- lifecycle -----------------------------

    @property
    def active(self) -> int:
        return len(self._tasks) + sum(c.active for c in self._children)

    def cancel(self) -> None:
        """Cascade-cancel this tracker and every descendant."""
        self._cancelled = True
        for t in list(self._tasks):
            t.cancel()
        for c in self._children:
            c.cancel()

    async def join(self) -> None:
        """Wait for all tasks (and children's tasks) to settle."""
        while True:
            pending = list(self._tasks)
            for c in self._children:
                pending.extend(c._tasks)
            if not pending:
                return
            await asyncio.gather(*pending, return_exceptions=True)
