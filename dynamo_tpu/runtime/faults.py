"""Deterministic fault injection for resilience tests.

A seeded :class:`FaultPlan` describes *where* (a named hook site), *when*
(after the Nth pass, for M firings, or with a seeded probability) and *what*
(drop the connection, delay a frame, truncate the stream, reject with an
error code) goes wrong. Transport and store hook points consult the
installed plan on every pass; with no plan installed the checks are a single
``None`` comparison, so production paths pay nothing.

Sites wired in this repo (the ``key`` each site passes):

==========================  =============================================
site                        key
==========================  =============================================
``client.connect``          worker ``host:port`` the router dials
``client.send``             worker ``host:port`` a request is pushed to
``worker.admit``            request id arriving at the ingress server
``worker.stream``           request id, checked before each data frame
``store.call``              store op name (``put``, ``publish``, …)
``store.connect``           store ``host:port`` being (re)dialled
``store.watch``             watched key prefix at (re)subscribe time
``disagg.prefill``          request id, at remote-prefill execution start
``disagg.transfer``         request id, per KV push attempt (device or
                            relay; ``truncate`` corrupts the relay frame)
``disagg.inject``           request id arriving at the kv_inject ingress
``preempt.notice``          worker id receiving a maintenance notice
                            (``drop`` = the notice is lost: no evacuation,
                            the kill lands cold)
``preempt.evacuate``        seat id being evacuated (``drop`` = the seat's
                            handoff fails and it falls back to re-prefill;
                            ``delay`` = slow evacuation against the
                            deadline)
``engine.stall``            dispatch window id about to be dispatched
                            (``delay`` = the window wedges on device for
                            ``delay_s``, exercising the stall watchdog)
==========================  =============================================

Kinds and how sites interpret them:

- ``drop``      — fail the operation as a connection error (retryable
  ``ERR_UNAVAILABLE`` on the transport, ``StoreError`` on the store).
- ``reject``    — refuse with ``code`` (default ``ERR_OVERLOADED``).
- ``delay``     — ``await asyncio.sleep(delay_s)`` then proceed (slow
  worker / slow store).
- ``truncate``  — worker-side only: abruptly close the response connection
  mid-stream, exactly what a crashing worker looks like to the router.

Determinism: each rule fires on its own per-rule pass counter
(``after`` ≤ pass-index < ``after + times``), and probabilistic rules draw
from the plan's seeded RNG — identical call order ⇒ identical faults.

Usage in tests::

    plan = FaultPlan(seed=0)
    plan.truncate_stream("worker.stream", after=3)   # crash on the 4th frame
    install(plan)
    try:
        ...drive the stack...
    finally:
        clear()
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from random import Random
from typing import List, Optional

DROP = "drop"
REJECT = "reject"
DELAY = "delay"
TRUNCATE = "truncate"


@dataclass
class FaultRule:
    site: str
    kind: str
    match: Optional[str] = None    # substring of the site key; None = any
    after: int = 0                 # matching passes to let through first
    times: Optional[int] = None    # firings before the rule burns out (None = forever)
    delay_s: float = 0.0
    code: str = "overloaded"       # reject code (transport error code)
    prob: float = 1.0              # per-pass firing probability (plan RNG)
    seen: int = field(default=0, compare=False)   # matching passes observed
    fired: int = field(default=0, compare=False)  # times actually fired


@dataclass
class FaultEvent:
    """One firing, recorded on the plan for post-hoc assertions."""

    site: str
    key: str
    kind: str


class FaultPlan:
    """A seeded set of fault rules plus a log of every firing."""

    def __init__(self, seed: int = 0):
        self.rng = Random(seed)
        self.rules: List[FaultRule] = []
        self.log: List[FaultEvent] = []

    # -- builders --

    def add(self, rule: FaultRule) -> "FaultPlan":
        self.rules.append(rule)
        return self

    def drop_connection(self, site: str, match: Optional[str] = None,
                        after: int = 0, times: Optional[int] = None,
                        prob: float = 1.0) -> "FaultPlan":
        return self.add(FaultRule(site, DROP, match, after, times, prob=prob))

    def reject(self, site: str, match: Optional[str] = None,
               after: int = 0, times: Optional[int] = None,
               code: str = "overloaded") -> "FaultPlan":
        return self.add(FaultRule(site, REJECT, match, after, times, code=code))

    def delay(self, site: str, delay_s: float, match: Optional[str] = None,
              after: int = 0, times: Optional[int] = None) -> "FaultPlan":
        return self.add(FaultRule(site, DELAY, match, after, times,
                                  delay_s=delay_s))

    def truncate_stream(self, site: str = "worker.stream",
                        match: Optional[str] = None, after: int = 0,
                        times: Optional[int] = 1) -> "FaultPlan":
        return self.add(FaultRule(site, TRUNCATE, match, after, times))

    # -- evaluation --

    def check(self, site: str, key: str = "") -> Optional[FaultRule]:
        """First rule that fires at this (site, key) pass, advancing the
        per-rule pass counters. At most one rule fires per pass."""
        for rule in self.rules:
            if rule.site != site:
                continue
            if rule.match is not None and rule.match not in key:
                continue
            idx = rule.seen
            rule.seen += 1
            if idx < rule.after:
                continue
            if rule.times is not None and rule.fired >= rule.times:
                continue
            if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                continue
            rule.fired += 1
            self.log.append(FaultEvent(site, key, rule.kind))
            return rule
        return None

    def fired(self, site: Optional[str] = None) -> int:
        return sum(1 for e in self.log if site is None or e.site == site)


# The active plan is process-global: the test harness owns the whole stack
# (frontend, router, workers) in one process, so a single installation
# covers every layer.
_PLAN: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> None:
    global _PLAN
    _PLAN = plan


def clear() -> None:
    global _PLAN
    _PLAN = None


def active(site: str, key: str = "") -> Optional[FaultRule]:
    """Hook-site entry point: None when no plan is installed (fast path)."""
    if _PLAN is None:
        return None
    return _PLAN.check(site, key)


async def maybe_delay(rule: Optional[FaultRule]) -> Optional[FaultRule]:
    """Apply a delay rule in place (returns the rule for further handling)."""
    if rule is not None and rule.kind == DELAY and rule.delay_s > 0:
        await asyncio.sleep(rule.delay_s)
    return rule
