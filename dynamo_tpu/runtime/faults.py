"""Deterministic fault injection for resilience tests.

A seeded :class:`FaultPlan` describes *where* (a named hook site), *when*
(after the Nth pass, for M firings, or with a seeded probability) and *what*
(drop the connection, delay a frame, truncate the stream, reject with an
error code) goes wrong. Transport and store hook points consult the
installed plan on every pass; with no plan installed the checks are a single
``None`` comparison, so production paths pay nothing.

Sites wired in this repo (the ``key`` each site passes):

==========================  =============================================
site                        key
==========================  =============================================
``client.connect``          worker ``host:port`` the router dials
``client.send``             worker ``host:port`` a request is pushed to
``worker.admit``            request id arriving at the ingress server
``worker.stream``           request id, checked before each data frame
``store.call``              store op name (``put``, ``publish``, …)
``store.connect``           store ``host:port`` being (re)dialled
``store.watch``             watched key prefix at (re)subscribe time
``disagg.prefill``          request id, at remote-prefill execution start
``disagg.transfer``         request id, per KV push attempt (device or
                            relay; ``truncate`` corrupts the relay frame)
``disagg.inject``           request id arriving at the kv_inject ingress
``preempt.notice``          worker id receiving a maintenance notice
                            (``drop`` = the notice is lost: no evacuation,
                            the kill lands cold)
``preempt.evacuate``        seat id being evacuated (``drop`` = the seat's
                            handoff fails and it falls back to re-prefill;
                            ``delay`` = slow evacuation against the
                            deadline)
``engine.stall``            ``kind:window_id`` of the window about to be
                            dispatched — kind is ``decode``/``prefill``/
                            ``mixed`` (``delay`` = the window wedges on
                            device for ``delay_s``, exercising the stall
                            watchdog; match ``decode`` to wedge a window
                            whose deadline the delay reliably exceeds)
==========================  =============================================

Kinds and how sites interpret them:

- ``drop``      — fail the operation as a connection error (retryable
  ``ERR_UNAVAILABLE`` on the transport, ``StoreError`` on the store).
- ``reject``    — refuse with ``code`` (default ``ERR_OVERLOADED``).
- ``delay``     — ``await asyncio.sleep(delay_s)`` then proceed (slow
  worker / slow store).
- ``truncate``  — worker-side only: abruptly close the response connection
  mid-stream, exactly what a crashing worker looks like to the router.

Determinism: each rule fires on its own per-rule pass counter
(``after`` ≤ pass-index < ``after + times``), and probabilistic rules draw
from the plan's seeded RNG — identical call order ⇒ identical faults.

Usage in tests::

    plan = FaultPlan(seed=0)
    plan.truncate_stream("worker.stream", after=3)   # crash on the 4th frame
    install(plan)
    try:
        ...drive the stack...
    finally:
        clear()

Wire serialization: :meth:`FaultPlan.to_json` / :meth:`FaultPlan.from_json`
round-trip a plan (schema version ``SCHEMA_VERSION``) including its seed,
rule state (``seen``/``fired``) and the number of RNG draws consumed, so a
deserialized plan fires *identically* to the original under the same
subsequent call order — the property that lets a replay trace ship the same
fault schedule to the in-process SimCluster and, via the system server's
``/debug/faults`` endpoint, to live worker processes.

Rules may carry a ``wave`` tag (the replay event track's correlated
fault-wave name); :meth:`FaultPlan.clear_wave` retires one wave's rules
without disturbing the rest, and every :class:`FaultEvent` records the wave
of the rule that fired so post-hoc attribution can group firings per wave.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional, Union

DROP = "drop"
REJECT = "reject"
DELAY = "delay"
TRUNCATE = "truncate"
KINDS = (DROP, REJECT, DELAY, TRUNCATE)

# wire-format version for FaultPlan.to_json/from_json; bump on any change
# that an older reader would misinterpret
SCHEMA_VERSION = 1


@dataclass
class FaultRule:
    site: str
    kind: str
    match: Optional[str] = None    # substring of the site key; None = any
    after: int = 0                 # matching passes to let through first
    times: Optional[int] = None    # firings before the rule burns out (None = forever)
    delay_s: float = 0.0
    code: str = "overloaded"       # reject code (transport error code)
    prob: float = 1.0              # per-pass firing probability (plan RNG)
    wave: Optional[str] = None     # replay fault-wave tag (attribution group)
    seen: int = field(default=0, compare=False)   # matching passes observed
    fired: int = field(default=0, compare=False)  # times actually fired

    def to_dict(self) -> dict:
        return {
            "site": self.site, "kind": self.kind, "match": self.match,
            "after": self.after, "times": self.times,
            "delay_s": self.delay_s, "code": self.code, "prob": self.prob,
            "wave": self.wave, "seen": self.seen, "fired": self.fired,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        if d.get("kind") not in KINDS:
            raise ValueError(f"unknown fault kind: {d.get('kind')!r}")
        rule = cls(
            site=d["site"], kind=d["kind"], match=d.get("match"),
            after=int(d.get("after", 0)),
            times=None if d.get("times") is None else int(d["times"]),
            delay_s=float(d.get("delay_s", 0.0)),
            code=d.get("code", "overloaded"),
            prob=float(d.get("prob", 1.0)),
            wave=d.get("wave"),
        )
        rule.seen = int(d.get("seen", 0))
        rule.fired = int(d.get("fired", 0))
        return rule


@dataclass
class FaultEvent:
    """One firing, recorded on the plan for post-hoc assertions."""

    site: str
    key: str
    kind: str
    wave: Optional[str] = None


class FaultPlan:
    """A seeded set of fault rules plus a log of every firing."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = Random(seed)
        self.rules: List[FaultRule] = []
        self.log: List[FaultEvent] = []
        self._draws = 0  # seeded-RNG draws consumed (serialized for replay)

    # -- builders --

    def add(self, rule: FaultRule) -> "FaultPlan":
        self.rules.append(rule)
        return self

    def drop_connection(self, site: str, match: Optional[str] = None,
                        after: int = 0, times: Optional[int] = None,
                        prob: float = 1.0, wave: Optional[str] = None
                        ) -> "FaultPlan":
        return self.add(FaultRule(site, DROP, match, after, times, prob=prob,
                                  wave=wave))

    def reject(self, site: str, match: Optional[str] = None,
               after: int = 0, times: Optional[int] = None,
               code: str = "overloaded", wave: Optional[str] = None
               ) -> "FaultPlan":
        return self.add(FaultRule(site, REJECT, match, after, times, code=code,
                                  wave=wave))

    def delay(self, site: str, delay_s: float, match: Optional[str] = None,
              after: int = 0, times: Optional[int] = None,
              wave: Optional[str] = None) -> "FaultPlan":
        return self.add(FaultRule(site, DELAY, match, after, times,
                                  delay_s=delay_s, wave=wave))

    def truncate_stream(self, site: str = "worker.stream",
                        match: Optional[str] = None, after: int = 0,
                        times: Optional[int] = 1,
                        wave: Optional[str] = None) -> "FaultPlan":
        return self.add(FaultRule(site, TRUNCATE, match, after, times,
                                  wave=wave))

    # -- evaluation --

    def check(self, site: str, key: str = "") -> Optional[FaultRule]:
        """First rule that fires at this (site, key) pass, advancing the
        per-rule pass counters. At most one rule fires per pass."""
        for rule in self.rules:
            if rule.site != site:
                continue
            if rule.match is not None and rule.match not in key:
                continue
            idx = rule.seen
            rule.seen += 1
            if idx < rule.after:
                continue
            if rule.times is not None and rule.fired >= rule.times:
                continue
            if rule.prob < 1.0:
                self._draws += 1
                if self.rng.random() >= rule.prob:
                    continue
            rule.fired += 1
            self.log.append(FaultEvent(site, key, rule.kind, wave=rule.wave))
            return rule
        return None

    def fired(self, site: Optional[str] = None) -> int:
        return sum(1 for e in self.log if site is None or e.site == site)

    def fired_counts(self) -> Dict[str, int]:
        """Firing counts keyed ``site/kind`` — the cross-mode parity unit
        (SimCluster vs live-HTTP replays must agree on these counts)."""
        counts: Dict[str, int] = {}
        for e in self.log:
            k = f"{e.site}/{e.kind}"
            counts[k] = counts.get(k, 0) + 1
        return counts

    # -- wave lifecycle --

    def clear_wave(self, wave: str) -> int:
        """Retire the rules of one fault wave (the firing log is kept for
        attribution). Returns the number of rules removed."""
        before = len(self.rules)
        self.rules = [r for r in self.rules if r.wave != wave]
        return before - len(self.rules)

    # -- wire serialization --

    def to_dict(self, include_log: bool = False) -> dict:
        d = {
            "schema": SCHEMA_VERSION,
            "seed": self.seed,
            "draws": self._draws,
            "rules": [r.to_dict() for r in self.rules],
        }
        if include_log:
            d["log"] = [
                {"site": e.site, "key": e.key, "kind": e.kind, "wave": e.wave}
                for e in self.log
            ]
        return d

    def to_json(self, include_log: bool = False) -> str:
        return json.dumps(self.to_dict(include_log=include_log),
                          sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        schema = d.get("schema")
        if schema != SCHEMA_VERSION:
            raise ValueError(
                f"unsupported FaultPlan schema {schema!r} "
                f"(this reader speaks {SCHEMA_VERSION})")
        plan = cls(seed=int(d.get("seed", 0)))
        # burn the draws the original already consumed so the deserialized
        # plan continues the exact same random sequence
        for _ in range(int(d.get("draws", 0))):
            plan.rng.random()
        plan._draws = int(d.get("draws", 0))
        for rd in d.get("rules", []):
            plan.add(FaultRule.from_dict(rd))
        for ed in d.get("log", []):
            plan.log.append(FaultEvent(ed["site"], ed.get("key", ""),
                                       ed["kind"], wave=ed.get("wave")))
        return plan

    @classmethod
    def from_json(cls, data: Union[str, bytes]) -> "FaultPlan":
        return cls.from_dict(json.loads(data))


# The active plan is process-global: the test harness owns the whole stack
# (frontend, router, workers) in one process, so a single installation
# covers every layer.
_PLAN: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> None:
    global _PLAN
    _PLAN = plan


def clear() -> None:
    global _PLAN
    _PLAN = None


def current() -> Optional[FaultPlan]:
    """The installed plan, if any (introspection: /debug/faults, snapshots)."""
    return _PLAN


def active(site: str, key: str = "") -> Optional[FaultRule]:
    """Hook-site entry point: None when no plan is installed (fast path)."""
    if _PLAN is None:
        return None
    return _PLAN.check(site, key)


async def maybe_delay(rule: Optional[FaultRule]) -> Optional[FaultRule]:
    """Apply a delay rule in place (returns the rule for further handling)."""
    if rule is not None and rule.kind == DELAY and rule.delay_s > 0:
        await asyncio.sleep(rule.delay_s)
    return rule
