"""Request contexts: identity, distributed trace, hierarchical cancellation.

Equivalent to the reference's ``AsyncEngineContext`` (ref: lib/runtime/src/
engine.rs:112): every in-flight request carries an id, a trace context, and
two cancellation levels — ``stop_generating`` (graceful: finish the current
token, emit what we have) and ``kill`` (abandon the stream). Contexts form a
tree via ``link_child`` so cancelling upstream propagates downstream
(ref: docs/architecture/request_cancellation.md).

A context may also carry a **deadline** (absolute ``time.monotonic()``
seconds): the total wall-clock budget the request may spend across every
retry, migration, and queue it rides. The deadline propagates to children
and across the transport (as a remaining-budget header), so a worker stops
generating for a request whose client has already given up.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import List, Optional

from ..utils.logging import TraceContext


class Context:
    def __init__(
        self,
        request_id: Optional[str] = None,
        trace: Optional[TraceContext] = None,
        deadline: Optional[float] = None,
    ):
        self.id: str = request_id or uuid.uuid4().hex
        self.trace: TraceContext = trace or TraceContext.new()
        self.deadline: Optional[float] = deadline
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: List["Context"] = []

    @classmethod
    def with_timeout(
        cls,
        timeout_s: Optional[float],
        request_id: Optional[str] = None,
        trace: Optional[TraceContext] = None,
    ) -> "Context":
        """Context whose deadline is ``timeout_s`` from now (None = no bound)."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        return cls(request_id=request_id, trace=trace, deadline=deadline)

    # -- deadline --

    def time_remaining(self) -> Optional[float]:
        """Seconds left in the budget (may be negative); None when unbounded."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def is_expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline

    # -- cancellation tree --

    def link_child(self, child: "Context") -> "Context":
        self._children.append(child)
        if child.deadline is None:
            child.deadline = self.deadline
        elif self.deadline is not None:
            child.deadline = min(child.deadline, self.deadline)
        if self.is_stopped():
            child.stop_generating()
        if self.is_killed():
            child.kill()
        return child

    def child(self) -> "Context":
        return self.link_child(
            Context(request_id=self.id, trace=self.trace.child(),
                    deadline=self.deadline)
        )

    def stop_generating(self) -> None:
        self._stopped.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()
        for c in self._children:
            c.kill()

    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    async def wait_killed(self) -> None:
        await self._killed.wait()
