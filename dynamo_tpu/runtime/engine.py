"""The streaming engine abstraction and pipeline operator graph.

``AsyncEngine`` is the universal unit of composition (ref: lib/runtime/src/
engine.rs:201): a single request in, an async stream of responses out, with a
:class:`Context` for cancellation. ``Operator`` is a bidirectional pipeline
stage (ref: lib/runtime/src/pipeline.rs:31-58): it transforms the request on
the forward edge and the response stream on the backward edge. ``link``
chains operators into a served pipeline exactly like the reference's
``frontend → preprocessor → backend → migration → router`` chain
(ref: lib/llm/src/entrypoint/input/common.rs:226,303-310).
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Generic, List, Optional, TypeVar

from .context import Context

Req = TypeVar("Req")
Resp = TypeVar("Resp")


class AsyncEngine(abc.ABC, Generic[Req, Resp]):
    """SingleIn → ManyOut streaming engine."""

    @abc.abstractmethod
    def generate(
        self, request: Req, context: Context
    ) -> AsyncIterator[Resp]:
        """Return an async iterator of responses for one request."""
        raise NotImplementedError


class Operator(abc.ABC):
    """A bidirectional pipeline stage.

    ``forward`` maps the incoming request to the downstream request type;
    ``backward`` wraps the downstream response stream into the upstream
    response type. Either may consult/extend the :class:`Context`.
    """

    async def forward(self, request: Any, context: Context) -> Any:
        return request

    def backward(
        self, stream: AsyncIterator[Any], request: Any, context: Context
    ) -> AsyncIterator[Any]:
        return stream


class _Linked(AsyncEngine):
    def __init__(self, operators: List[Operator], sink: AsyncEngine):
        self._operators = operators
        self._sink = sink

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        # forward edge: outermost operator first
        requests = [request]
        for op in self._operators:
            request = await op.forward(request, context)
            requests.append(request)
        stream = self._sink.generate(request, context)
        # backward edge: innermost operator first, each sees the request as it
        # existed at its own depth on the forward pass
        for op, req_at_depth in zip(reversed(self._operators), reversed(requests[:-1])):
            stream = op.backward(stream, req_at_depth, context)
        async for item in stream:
            yield item


def link(*stages: Any) -> AsyncEngine:
    """Chain operators ending in an AsyncEngine sink into one AsyncEngine."""
    if not stages:
        raise ValueError("link() needs at least a sink engine")
    *ops, sink = stages
    if not isinstance(sink, AsyncEngine):
        raise TypeError("last stage must be an AsyncEngine")
    for op in ops:
        if not isinstance(op, Operator):
            raise TypeError(f"intermediate stage {op!r} must be an Operator")
    return _Linked(list(ops), sink)


class FnEngine(AsyncEngine):
    """Adapt an ``async generator function (request, context)`` to AsyncEngine."""

    def __init__(self, fn):
        self._fn = fn

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._fn(request, context)
