"""Radix-tree prefix index over the tiered KV cache.

The global prefix cache's source of truth: WHICH prefix blocks exist,
WHICH tier holds each one (G1 device HBM pages, G2 byte-bounded host
pool, G4 store remote tier), and on WHICH worker. The engine-side
:class:`~dynamo_tpu.prefix.manager.PrefixCacheManager` feeds it from
pool/kvbm events; the router feeds a cluster-wide replica from
``RouterEvent`` streams and scores workers by longest cached prefix.

Keying (why this is a radix tree without storing token edges): block
keys are the *chained* sequence hashes from ``tokens.py`` —
``xxh3_64(parent_seq_hash || token_bytes)`` — so equal keys imply equal
full prefixes and a node's key doubles as its path digest. Edges are
just ``parent seq_hash -> child seq_hash`` links; a divergent
continuation of a shared prefix inserts a new child under the shared
parent, which is the radix split without ever copying the shared run.
Only complete blocks are hashed (``compute_block_hashes_for_seq``
ignores the ragged tail), so partial trailing blocks can never be
indexed — the block-aligned boundary invariant the tests pin.

Recency uses a logical clock (monotone per-index counter), never wall
time, so eviction order is a pure function of the operation sequence —
seeded churn schedules replay deterministically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

TIER_G1 = "g1"   # device HBM paged cache (the engine's BlockPool)
TIER_G2 = "g2"   # host LRU pool (block- and byte-bounded)
TIER_G4 = "g4"   # cluster-shared store remote tier
TIERS = (TIER_G1, TIER_G2, TIER_G4)

# Routing score weight per tier: a G1 hit serves immediately; G2/G4 hits
# save the prefill FLOPs but pay an onboard copy, so they count for less
# when ranking workers by longest cached prefix.
DEFAULT_TIER_WEIGHTS = {TIER_G1: 1.0, TIER_G2: 0.75, TIER_G4: 0.5}


@dataclass
class RadixNode:
    """One complete prefix block. ``seq_hash`` is both the node key and
    the prefix digest of its whole root path (chained hashing)."""

    seq_hash: int
    block_hash: int
    parent: Optional[int]
    depth: int                       # blocks from the root (>= 1)
    children: Set[int] = field(default_factory=set)
    # tier -> workers holding this block in that tier
    holders: Dict[str, Set[int]] = field(
        default_factory=lambda: {t: set() for t in TIERS})
    last_use: int = 0                # logical clock, not wall time

    def workers(self, tier: Optional[str] = None) -> Set[int]:
        if tier is not None:
            return self.holders[tier]
        out: Set[int] = set()
        for ws in self.holders.values():
            out |= ws
        return out

    def empty(self) -> bool:
        return not any(self.holders.values())


@dataclass
class PrefixMatch:
    """Longest-leading-run match for one request's hash chain."""

    blocks: int = 0                  # matched leading complete blocks
    nodes: List[RadixNode] = field(default_factory=list)
    # per-worker weighted score over that worker's own leading run
    scores: Dict[int, float] = field(default_factory=dict)
    # per-worker unweighted leading blocks (any tier on that worker)
    worker_blocks: Dict[int, int] = field(default_factory=dict)


class RadixPrefixIndex:
    """Block-aligned radix prefix index with per-node tier/worker state.

    Deterministic by construction: insertion order only affects logical
    clock values, and every tie in eviction breaks on ``seq_hash`` — the
    same operation sequence always evicts the same subtrees.
    """

    def __init__(self, block_size: int,
                 tier_weights: Optional[Dict[str, float]] = None):
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self.block_size = block_size
        self.tier_weights = dict(tier_weights or DEFAULT_TIER_WEIGHTS)
        self._nodes: Dict[int, RadixNode] = {}
        self._roots: Set[int] = set()
        # children inserted before their parent, keyed by the missing
        # parent hash — adopted when the parent arrives
        self._orphans: Dict[int, Set[int]] = {}
        self._clock = 0
        # accounting the replay scoreboard cross-checks against the
        # scheduler's own measured hit counters (prefix_vs_index)
        self.hit_tokens_total = 0
        self.queries_total = 0
        self.evictions_total = 0
        self.inserted_total = 0

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._nodes

    def get(self, seq_hash: int) -> Optional[RadixNode]:
        return self._nodes.get(seq_hash)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ------------------------------ insert -----------------------------

    def insert(self, seq_hash: int, block_hash: int,
               parent: Optional[int], tier: str, worker: int) -> RadixNode:
        """Index one sealed block for ``worker`` in ``tier``.

        The radix split is implicit: a continuation diverging after a
        shared run adds a child under the shared parent node; the shared
        nodes are reused, never copied. A parent evicted from the index
        leaves the child as a detached root (depth restarts) — matching
        still works because lookups walk the request's own hash chain.
        """
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}")
        node = self._nodes.get(seq_hash)
        if node is None:
            pnode = self._nodes.get(parent) if parent is not None else None
            node = RadixNode(
                seq_hash=seq_hash, block_hash=block_hash, parent=parent,
                depth=(pnode.depth + 1) if pnode is not None else 1,
            )
            self._nodes[seq_hash] = node
            if pnode is not None:
                pnode.children.add(seq_hash)
            else:
                self._roots.add(seq_hash)
                if parent is not None:
                    self._orphans.setdefault(parent, set()).add(seq_hash)
            # adopt any children that arrived before this node; their
            # subtrees were rooted at depth 1 while detached, so re-walk
            # them — depths must be a pure function of the final tree,
            # not of insertion order
            for c in sorted(self._orphans.pop(seq_hash, ())):
                child = self._nodes.get(c)
                if child is not None and child.parent == seq_hash:
                    node.children.add(c)
                    self._roots.discard(c)
                    self._redepth(c, node.depth + 1)
            self.inserted_total += 1
        node.holders[tier].add(worker)
        node.last_use = self._tick()
        return node

    def _redepth(self, seq_hash: int, depth: int) -> None:
        stack = [(seq_hash, depth)]
        while stack:
            h, d = stack.pop()
            node = self._nodes.get(h)
            if node is None:
                continue
            node.depth = d
            stack.extend((c, d + 1) for c in node.children)

    # ------------------------- tier transitions ------------------------

    def mark(self, seq_hash: int, tier: str, worker: int) -> bool:
        """Record that ``worker`` now holds the block in ``tier`` (e.g.
        an offload landed it in G2). No-op if the node is unknown."""
        node = self._nodes.get(seq_hash)
        if node is None:
            return False
        node.holders[tier].add(worker)
        node.last_use = self._tick()
        return True

    def unmark(self, seq_hash: int, tier: str,
               worker: Optional[int] = None) -> bool:
        """Drop ``worker``'s (or every worker's) holding in ``tier``;
        prunes the node once no tier holds it anywhere."""
        node = self._nodes.get(seq_hash)
        if node is None:
            return False
        if worker is None:
            node.holders[tier].clear()
        else:
            node.holders[tier].discard(worker)
        self._prune_if_empty(node)
        return True

    def drop_worker(self, worker: int) -> int:
        """Purge every holding of ``worker`` (worker removed from the
        fleet). Returns nodes touched."""
        touched = 0
        for node in list(self._nodes.values()):
            hit = False
            for ws in node.holders.values():
                if worker in ws:
                    ws.discard(worker)
                    hit = True
            if hit:
                touched += 1
                self._prune_if_empty(node)
        return touched

    def clear_worker_tier(self, worker: int, tier: str) -> int:
        """Drop every ``tier`` holding of ``worker`` (pool cleared)."""
        n = 0
        for node in list(self._nodes.values()):
            if worker in node.holders[tier]:
                node.holders[tier].discard(worker)
                n += 1
                self._prune_if_empty(node)
        return n

    def _prune_if_empty(self, node: RadixNode) -> None:
        """Remove hold-free leaves, walking up: an interior hold-free
        node stays as structure while any descendant is still held."""
        while node is not None and node.empty() and not node.children:
            self._nodes.pop(node.seq_hash, None)
            self._roots.discard(node.seq_hash)
            if node.parent is not None:
                waiting = self._orphans.get(node.parent)
                if waiting is not None:
                    waiting.discard(node.seq_hash)
                    if not waiting:
                        del self._orphans[node.parent]
            parent = (self._nodes.get(node.parent)
                      if node.parent is not None else None)
            if parent is not None:
                parent.children.discard(node.seq_hash)
            node = parent

    # ------------------------------ match ------------------------------

    def find_matches(self, hashes: Sequence[int]) -> PrefixMatch:
        """Longest-leading-run match of a request's chained hash chain.

        ``scores[w]`` sums tier weights over worker ``w``'s own leading
        run (its best tier per block), so a worker holding 8 G1 blocks
        outranks one holding 8 G4 blocks — the router feeds these into
        ``select_worker`` in place of the flat overlap counts. Counts as
        one query for the hit-rate accounting.
        """
        self.queries_total += 1
        match = PrefixMatch()
        alive: Optional[Set[int]] = None   # workers with an unbroken run
        for h in hashes:
            node = self._nodes.get(h)
            if node is None or node.empty():
                break
            match.blocks += 1
            match.nodes.append(node)
            node.last_use = self._tick()
            here = node.workers()
            alive = set(here) if alive is None else (alive & here)
            if not alive:
                # the global chain continues (someone holds this block)
                # but no single worker holds the whole run — per-worker
                # scores stop growing, global match keeps walking
                continue
            for w in alive:
                best = 0.0
                for tier in TIERS:
                    if w in node.holders[tier]:
                        best = max(best, self.tier_weights.get(tier, 0.0))
                match.scores[w] = match.scores.get(w, 0.0) + best
                match.worker_blocks[w] = match.worker_blocks.get(w, 0) + 1
        return match

    def longest_prefix_blocks(self, hashes: Sequence[int],
                              tier: Optional[str] = None,
                              worker: Optional[int] = None) -> int:
        """Leading blocks of ``hashes`` held (optionally: in ``tier``,
        by ``worker``). Read-only — no recency touch, no query count."""
        n = 0
        for h in hashes:
            node = self._nodes.get(h)
            if node is None:
                break
            if tier is not None:
                ws = node.holders[tier]
            else:
                ws = node.workers()
            if worker is not None:
                if worker not in ws:
                    break
            elif not ws:
                break
            n += 1
        return n

    # --------------------------- hit accounting ------------------------

    def record_hit_blocks(self, hashes: Iterable[int], tier: str,
                          worker: int) -> int:
        """Count served-from-cache blocks, verifying each against the
        index's own tier state — the independent accounting the replay
        ``prefix_vs_index`` cross-check compares with the scheduler's
        measured hits. Returns hit tokens credited."""
        tokens = 0
        for h in hashes:
            node = self._nodes.get(h)
            if node is None or worker not in node.holders[tier]:
                continue
            node.last_use = self._tick()
            tokens += self.block_size
        self.hit_tokens_total += tokens
        return tokens

    # ------------------------------ evict ------------------------------

    def _subtree(self, seq_hash: int) -> List[RadixNode]:
        out: List[RadixNode] = []
        stack = [seq_hash]
        while stack:
            node = self._nodes.get(stack.pop())
            if node is None:
                continue
            out.append(node)
            stack.extend(sorted(node.children))
        return out

    def lru_subtree(self, tier: str, worker: Optional[int] = None,
                    exclude_roots: Optional[Set[int]] = None) -> List[int]:
        """Pick the LRU eviction victim for one tier WITHOUT mutating.

        Let ``sub_last(n)`` be the most recent use anywhere in ``n``'s
        held subtree. A node is a candidate victim root when evicting
        its whole subtree removes only cold state: its parent is not
        held (or the parent's subtree contains something strictly more
        recent — i.e. this subtree is maximal among all-cold subtrees).
        The candidate with the oldest ``sub_last`` wins, so a whole cold
        conversation branch goes at once while a hot shared run is never
        punched through. Ties break on ``seq_hash``; recency is the
        logical clock, so the choice is a pure function of the operation
        sequence. Returns the subtree's held hashes, root first (empty =
        nothing evictable)."""
        def held(n: RadixNode) -> bool:
            ws = n.holders[tier]
            return (worker in ws) if worker is not None else bool(ws)

        sub_last: Dict[int, int] = {}

        def compute_sub_last(h: int) -> int:
            cached = sub_last.get(h)
            if cached is not None:
                return cached
            node = self._nodes[h]
            last = node.last_use if held(node) else 0
            for c in node.children:
                last = max(last, compute_sub_last(c))
            sub_last[h] = last
            return last

        candidates: List[Tuple[int, int]] = []
        for node in self._nodes.values():
            if not held(node):
                continue
            if exclude_roots and node.seq_hash in exclude_roots:
                continue
            mine = compute_sub_last(node.seq_hash)
            pnode = (self._nodes.get(node.parent)
                     if node.parent is not None else None)
            if pnode is not None and held(pnode) \
                    and compute_sub_last(pnode.seq_hash) <= mine:
                continue   # parent's subtree is just as cold — not maximal
            candidates.append((mine, node.seq_hash))
        if not candidates:
            return []
        candidates.sort()
        victim = candidates[0][1]
        return [n.seq_hash for n in self._subtree(victim) if held(n)]

    def evict_lru_subtree(self, tier: str,
                          worker: Optional[int] = None) -> List[int]:
        """LRU-by-subtree eviction: :meth:`lru_subtree` then drop the
        tier holdings for the whole victim subtree. Returns the evicted
        hashes (caller demotes/frees the actual payloads)."""
        evicted = self.lru_subtree(tier, worker)
        for h in evicted:
            node = self._nodes.get(h)
            if node is None:
                continue
            if worker is None:
                node.holders[tier].clear()
            else:
                node.holders[tier].discard(worker)
        # prune leaf-first so interior nodes see updated children sets
        for h in reversed(evicted):
            node = self._nodes.get(h)
            if node is not None:
                self._prune_if_empty(node)
        self.evictions_total += len(evicted)
        return evicted

    # --------------------------- router events -------------------------

    def apply_event(self, worker_id: int, event: dict) -> None:
        """Feed one ``RouterEvent`` payload (``{"kind", "blocks"}``).

        ``stored`` blocks carry the prefix-node digest chain
        (``digest`` = chained seq_hash; ``parent`` links) plus an
        optional ``tier`` (default G1 — engine pool events). The router
        keeps a cluster replica of this index from these alone.
        """
        kind = event.get("kind")
        if kind == "stored":
            for b in event.get("blocks", ()):
                h = b.get("digest", b.get("seq_hash"))
                if h is None:
                    continue
                self.insert(int(h), int(b.get("block_hash", h)),
                            b.get("parent"), b.get("tier", TIER_G1),
                            worker_id)
        elif kind == "removed":
            for h in event.get("blocks", ()):
                self.unmark(int(h), event.get("tier", TIER_G1), worker_id)
        elif kind == "cleared":
            self.clear_worker_tier(worker_id, event.get("tier", TIER_G1))

    # ------------------------------ stats ------------------------------

    def tier_blocks(self, tier: str,
                    worker: Optional[int] = None) -> int:
        if worker is None:
            return sum(1 for n in self._nodes.values()
                       if n.holders[tier])
        return sum(1 for n in self._nodes.values()
                   if worker in n.holders[tier])

    def stats(self) -> Dict[str, float]:
        return {
            "prefix_nodes": float(len(self._nodes)),
            "prefix_hit_tokens_total": float(self.hit_tokens_total),
            "prefix_queries_total": float(self.queries_total),
            "prefix_evictions_total": float(self.evictions_total),
            "prefix_inserted_total": float(self.inserted_total),
        }

    def check_invariants(self) -> None:
        """Structural invariants (tests call this after churn): parent
        links and children sets agree, roots are exactly the parentless
        nodes, no node is hold-free AND childless."""
        for h, node in self._nodes.items():
            assert node.seq_hash == h
            if node.parent is not None and node.parent in self._nodes:
                assert h in self._nodes[node.parent].children, \
                    f"{h:x} missing from parent children"
            else:
                assert h in self._roots, f"{h:x} detached but not a root"
            for c in node.children:
                assert c in self._nodes, f"{h:x} has dangling child {c:x}"
                assert self._nodes[c].parent == h
            assert not (node.empty() and not node.children), \
                f"{h:x} is hold-free and childless — should be pruned"
        for r in self._roots:
            assert r in self._nodes
