"""Engine-side prefix cache manager over the tiered KVBM.

Owns one :class:`RadixPrefixIndex` tracking every complete prefix block
this worker knows about and which tier holds it:

  G1 (device HBM pages, the engine's ``BlockPool``) — fed by the pool's
      stored/removed/cleared events, so the index's G1 view tracks the
      paged cache exactly;
  G2 (host LRU pool) — marked when the KVBM offload tick lands a block,
      unmarked when the byte-bounded pool drops it;
  G4 (store remote tier) — marked on write-through puts.

On top of the index it adds the two tier *policies* the KVBM machinery
doesn't have: demotion (``evict_to_host`` — the planner degradation
ladder's new rung ahead of tier shedding: LRU subtrees of sealed G1
blocks are copied to the host pool and their HBM pages freed) and
device-plane onboarding (``onboard`` — a prompt whose prefix lives in a
*peer worker's* G1 is pulled block-for-block over the epoch-guarded
``disagg/ici.py`` transfer path instead of recomputed; G2/G4 hits fall
through to the KVBM onboard path, whose CRC-enveloped wire format and
per-(token, head) quantized scales keep the bytes exact at int8/fp8).

Hit accounting: the scheduler reports every admission-time prefix match
through ``on_scheduler_match``; the manager credits only blocks the
*index* also believes are in G1 — an independent state machine fed by
events — which is what the replay scoreboard's ``prefix_vs_index``
cross-check compares against the scheduler's own measured counters.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .radix import TIER_G1, TIER_G2, TIER_G4, RadixPrefixIndex

log = get_logger("prefix")


@dataclass
class PrefixCacheConfig:
    enabled: bool = True
    # per evict_to_host() call: how many G1 blocks one degradation-rung
    # application may demote (bounds the extract batch per tick)
    evict_to_host_blocks: int = 64
    # per-request bound on blocks pulled over the device plane
    max_ici_blocks: int = 512
    # routing score weights for non-G1 tiers (G1 = 1.0)
    tier_weight_g2: float = 0.75
    tier_weight_g4: float = 0.5


class PrefixCacheManager:
    """Attached to an :class:`EngineCore` via ``attach_prefix_cache``."""

    def __init__(self, engine, kvbm=None,
                 config: Optional[PrefixCacheConfig] = None,
                 worker_id: int = 0, plane=None):
        self.engine = engine
        self.kvbm = kvbm
        self.config = config or PrefixCacheConfig()
        self.worker_id = worker_id
        # disagg.ici.DevicePlane + worker_id -> plane_id of in-process
        # peers whose G1 blocks can be pulled device-to-device
        self.plane = plane
        self.peer_planes: Dict[int, str] = {}
        self.index = RadixPrefixIndex(
            engine.config.block_size,
            tier_weights={TIER_G1: 1.0,
                          TIER_G2: self.config.tier_weight_g2,
                          TIER_G4: self.config.tier_weight_g4},
        )
        self.demoted_blocks = 0
        self.ici_onboarded_blocks = 0
        if kvbm is not None:
            kvbm.prefix = self
            # G2 drops retract the index marking; chain whatever drop
            # hook (distributed presence retraction) is already installed
            prev_drop = kvbm.host_pool.on_drop

            def _on_drop(seq_hash: int) -> None:
                if prev_drop is not None:
                    prev_drop(seq_hash)
                self.index.unmark(seq_hash, TIER_G2, self.worker_id)

            kvbm.host_pool.on_drop = _on_drop

    # --------------------- event-driven tier state ---------------------

    def on_pool_event(self, event) -> None:
        """G1 mirror: called from the engine's KV-event hook with every
        BlockPool stored/removed/cleared event."""
        if event.kind == "stored":
            for b in event.blocks:
                self.index.insert(
                    b["seq_hash"], b.get("block_hash", b["seq_hash"]),
                    b.get("parent"), TIER_G1, self.worker_id)
        elif event.kind == "removed":
            for h in event.blocks:
                self.index.unmark(h, TIER_G1, self.worker_id)
        elif event.kind == "cleared":
            self.index.clear_worker_tier(self.worker_id, TIER_G1)

    def on_offloaded(self, seq_hash: int) -> None:
        """KVBM offload tick landed the block in the host pool."""
        self.index.mark(seq_hash, TIER_G2, self.worker_id)

    def on_g4_put(self, seq_hash: int) -> None:
        self.index.mark(seq_hash, TIER_G4, self.worker_id)

    def ingest_router_event(self, worker_id: int, event: dict) -> None:
        """Learn a PEER worker's tier state from its ``RouterEvent``
        stream (the same events the router's cluster replica consumes) —
        this is how ``_peer_runs`` knows which peer G1 holds a prefix.
        Own events are ignored; the local pool feed is authoritative."""
        if worker_id != self.worker_id:
            self.index.apply_event(worker_id, event)

    def on_scheduler_match(self, queried: List[int],
                           matched: List[int]) -> None:
        """Admission-time prefix match result from the scheduler: credit
        hit tokens against the index's own G1 view (the independent
        accounting ``prefix_vs_index`` cross-checks)."""
        self.index.queries_total += len(queried)
        self.index.record_hit_blocks(matched, TIER_G1, self.worker_id)

    # ----------------------------- stats -------------------------------

    def snapshot(self) -> Dict[str, float]:
        out = self.index.stats()
        out["prefix_demoted_total"] = float(self.demoted_blocks)
        out["prefix_ici_onboarded_total"] = float(self.ici_onboarded_blocks)
        return out

    # --------------------------- onboarding ----------------------------

    async def onboard(self, token_seq) -> int:
        """Promote cached leading blocks of a prompt into G1 before
        admission. Order: peer-G1 over the device plane (no host round
        trip), then the KVBM host/peer-G2/G4 chain. Returns blocks
        promoted."""
        if not self.config.enabled:
            return 0
        n = 0
        if self.plane is not None and self.peer_planes:
            try:
                n += await self._onboard_ici(token_seq)
            except Exception:
                log.exception("ici prefix onboard failed — falling back")
        if self.kvbm is not None:
            n += await self.kvbm.onboard_prefix(token_seq)
        return n

    async def _onboard_ici(self, token_seq) -> int:
        """Pull the longest peer-held G1 run device-to-device.

        Rides :meth:`DevicePlane.transfer` — the epoch-guarded path; the
        guard itself is idle here because adopted destination blocks are
        invisible to the prefix cache until ``release_adopted``, so a
        failed transfer can never publish half-written KV."""
        pool = self.engine.scheduler.pool
        hashes = [tb.sequence_hash for tb in token_seq.blocks]
        need_from = 0
        while (need_from < len(hashes)
               and pool.contains(hashes[need_from])):
            need_from += 1
        missing = hashes[need_from:]
        if not missing:
            return 0
        if self.kvbm is not None and missing[0] in self.kvbm.host_pool:
            return 0   # the host pool serves this run cheaper
        # longest leading run a single peer holds in G1 (ties: lowest id)
        best_worker, best_run = None, 0
        for w, run in sorted(self._peer_runs(missing).items()):
            if run > best_run:
                best_worker, best_run = w, run
        if best_worker is None or best_run <= 0:
            return 0
        src_engine = self.plane.get(self.peer_planes.get(best_worker))
        if src_engine is None:
            return 0
        src_pool = src_engine.scheduler.pool
        run = missing[: min(best_run, self.config.max_ici_blocks)]
        pinned: List[Tuple[int, int]] = []       # (src_bid, seq_hash)
        adopted: List[int] = []                  # dst block ids
        try:
            for i, h in enumerate(run):
                src_bid = src_pool.lookup(h)     # pins (incref)
                if src_bid is None:
                    break                        # peer evicted it — stop
                tb = token_seq.blocks[need_from + i]
                dst_bid = pool.adopt(h, tb.block_hash,
                                     tb.parent_sequence_hash)
                if dst_bid is None:              # local G1 full
                    src_pool.decref(src_bid)
                    break
                pinned.append((src_bid, h))
                adopted.append(dst_bid)
            if not adopted:
                return 0
            await self.plane.transfer(
                src_engine, [bid for bid, _ in pinned],
                self.engine, adopted)
        except BaseException:
            for bid in adopted:
                pool.discard_adopted(bid)
            for bid, _ in pinned:
                src_pool.decref(bid)
            raise
        for bid in adopted:
            pool.release_adopted(bid)
        for bid, _ in pinned:
            src_pool.decref(bid)
        self.ici_onboarded_blocks += len(adopted)
        log.info("onboarded %d prefix blocks from worker %d over the "
                 "device plane", len(adopted), best_worker)
        return len(adopted)

    def _peer_runs(self, hashes: List[int]) -> Dict[int, int]:
        """Leading G1 run length per peer worker for ``hashes``."""
        runs: Dict[int, int] = {}
        alive = set(self.peer_planes) - {self.worker_id}
        for h in hashes:
            node = self.index.get(h)
            if node is None:
                break
            alive &= node.holders[TIER_G1]
            if not alive:
                break
            for w in alive:
                runs[w] = runs.get(w, 0) + 1
        return runs

    # ---------------------------- demotion -----------------------------

    async def evict_to_host(self, max_blocks: Optional[int] = None) -> int:
        """The degradation ladder's evict-to-host rung: demote LRU
        subtrees of *sealed, unreferenced* G1 blocks to the host pool —
        one batched device gather — then free their HBM pages. Blocks
        still referenced by running sequences are skipped (and stay
        marked G1). Returns blocks demoted."""
        if self.kvbm is None:
            return 0
        pool = self.engine.scheduler.pool
        budget = max_blocks or self.config.evict_to_host_blocks
        victims: List[Tuple[int, int]] = []      # (seq_hash, block_id)
        tried: set = set()
        while len(victims) < budget:
            hashes = self.index.lru_subtree(
                TIER_G1, self.worker_id, exclude_roots=tried)
            if not hashes:
                break
            tried.add(hashes[0])
            for h in hashes:
                bid = pool._cached.get(h)
                if bid is None or bid not in pool._evictable:
                    continue   # in use by a running seq — not demotable
                pool.lookup(h)                   # pin while we gather
                victims.append((h, bid))
                if len(victims) >= budget:
                    break
        if not victims:
            return 0
        data = await self.engine.extract_kv_blocks(
            [bid for _, bid in victims])
        for i, (h, bid) in enumerate(victims):
            block = {key: arr[:, i].copy() for key, arr in data.items()}
            self.kvbm.host_pool.put(h, block)
            self.index.mark(h, TIER_G2, self.worker_id)
            # unregister the hash and free the page; the "removed" event
            # this emits is what clears the index's G1 marking
            pool.discard_adopted(bid)
        self.index.evictions_total += len(victims)
        self.demoted_blocks += len(victims)
        log.info("demoted %d G1 blocks to the host tier "
                 "(degradation evict_to_host)", len(victims))
        return len(victims)
