"""Global prefix cache: radix-tree prefix index over the tiered KVBM
(G1 HBM / G2 host / G4 store) with prefix-aware routing support."""

from .manager import PrefixCacheConfig, PrefixCacheManager
from .radix import (
    DEFAULT_TIER_WEIGHTS, TIER_G1, TIER_G2, TIER_G4, TIERS, PrefixMatch,
    RadixNode, RadixPrefixIndex,
)

__all__ = [
    "DEFAULT_TIER_WEIGHTS", "TIER_G1", "TIER_G2", "TIER_G4", "TIERS",
    "PrefixCacheConfig", "PrefixCacheManager", "PrefixMatch", "RadixNode",
    "RadixPrefixIndex",
]
