"""Simulated P/D cluster for closed-loop planner validation
(ref: the mocker-engine scale harness of components/backends/mocker —
speedup-accelerated engines faithful enough for control-loop dynamics).

Builds a real distributed deployment — store, per-worker
``DistributedRuntime`` + ingress server, KV-aware router, migration — whose
*engines* are simulated: token timing is driven by a small load model
instead of a device. That keeps every control-plane seam real (leases,
discovery, drains, breakers, migration carryover) while letting a hundred
workers and thousands of requests run on one CPU in seconds.

Load model:

- **TTFT** = wait for a slot in a global prefill pool (capacity = live
  prefill workers × slots) + ISL × per-token prefill cost + one decode
  step. Prefill workers are pure capacity: flipping a worker to prefill
  grows the pool, so the planner's prefill targets have real effect.
- **ITL** = per-worker decode step × max(1, active/seats): a decode worker
  running more streams than seats slows all of them, so overload shows up
  exactly where the planner looks (itl p99).
- Degradation orders feed back as cost scales (clamping spec_k /
  tightening chunking cheapens decode steps) and as admission tier
  shedding, so the ladder measurably relieves pressure before scaling.

Engines emit ScriptedWorker-convention tokens (1000 + absolute position)
so migrations and role flips are checked for byte-exact parity.

``SimCluster`` implements the orchestrator's ``WorkerPool`` protocol
(workers/spawn/stop/flip) plus ``kill`` for chaos. ``run_scenario`` closes
the whole loop: drive bursty Poisson/diurnal arrivals with seeded chaos
(worker kills, an optional store flap) against a live planner +
orchestrator and report per-window SLO compliance, recovery time, parity,
and per-tier latency percentiles.
"""

from __future__ import annotations

import asyncio
import math
import random
import socket
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..frontend.service import AdmissionController, AdmissionError, percentile
from ..llm.migration import Migration
from ..planner.connector import VirtualConnector
from ..planner.core import Planner, PlannerConfig, WindowMetrics
from ..planner.degradation import DegradationConfig, DegradationWatcher
from ..planner.interpolation import DecodeInterpolator, PrefillInterpolator
from ..planner.orchestrator import Orchestrator
from ..router.kv_router import KvPushRouter, KvRouter
from ..router.scheduler import KvRouterConfig
from ..runtime.circuit import BreakerConfig, CircuitBreakerRegistry
from ..runtime.component import DistributedRuntime
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..runtime.store import StoreServer
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger

log = get_logger("mocker.cluster")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ----------------------------- load model -------------------------------


@dataclass
class SimTiming:
    """Device-time costs; wall sleeps are divided by ``speedup_ratio``
    (ref: MockerConfig — the same acceleration knob)."""

    prefill_time_per_token_s: float = 10e-3
    decode_time_per_step_s: float = 160e-3
    speedup_ratio: float = 20.0
    prefill_slots_per_worker: int = 1
    decode_seats_per_worker: int = 1

    @property
    def eff_prefill_tpt(self) -> float:
        return self.prefill_time_per_token_s / self.speedup_ratio

    @property
    def eff_step(self) -> float:
        return self.decode_time_per_step_s / self.speedup_ratio

    def interpolators(self) -> Tuple[PrefillInterpolator, DecodeInterpolator]:
        """The profile an SLA profiler would record for these engines —
        ideal (uncongested) latency curves and the throughput/chip envelope
        the planner inverts."""
        step, tpt = self.eff_step, self.eff_prefill_tpt
        slots = self.prefill_slots_per_worker
        isl_grid = [8.0, 64.0, 512.0]
        # profiled TTFT includes the first (uncongested) decode step, like a
        # real profiler's time-to-first-token would
        prefill = PrefillInterpolator(
            isl=isl_grid,
            ttft_s=[isl * tpt + step for isl in isl_grid],
            thpt_per_chip=[slots / tpt] * len(isl_grid),
        )
        # conservative throughput envelope: the planner provisions headroom
        # below the factor-1 saturation rate (1/step tokens/s/worker)
        decode = DecodeInterpolator(
            kv_usage=[0.2, 0.5, 0.9, 0.2, 0.5, 0.9],
            context_length=[16.0, 16.0, 16.0, 512.0, 512.0, 512.0],
            itl_s=[step, step * 1.5, step * 3.0] * 2,
            thpt_per_chip=[0.5 / step, 0.65 / step, 0.8 / step] * 2,
        )
        return prefill, decode


class ResizablePool:
    """Counting pool whose capacity follows the live prefill fleet."""

    def __init__(self, capacity: int):
        self.capacity = max(1, capacity)
        self._in_use = 0
        self._cond = asyncio.Condition()

    @property
    def waiting(self) -> int:
        return len(self._cond._waiters)  # backlog signal for the planner

    async def acquire(self) -> None:
        async with self._cond:
            while self._in_use >= self.capacity:
                await self._cond.wait()
            self._in_use += 1

    async def release(self) -> None:
        async with self._cond:
            self._in_use -= 1
            self._cond.notify_all()

    async def resize(self, capacity: int) -> None:
        async with self._cond:
            self.capacity = max(1, capacity)
            self._cond.notify_all()


class SimWorkerEngine(AsyncEngine):
    """AsyncEngine with load-coupled timing and ScriptedWorker parity
    tokens: position ``j`` of the stream is token ``1000 + prompt_len + j``,
    so migrated/flipped continuations are byte-checkable."""

    def __init__(self, cluster: "SimCluster"):
        self.cluster = cluster
        self.active = 0  # decode streams running on this worker

    async def generate(self, request, context):
        cl = self.cluster
        t = cl.timing
        prompt = list(request["token_ids"])
        start = len(prompt)
        n = int(request.get("max_tokens", 8))
        await cl.prefill_pool.acquire()
        try:
            await asyncio.sleep(start * t.eff_prefill_tpt * cl.prefill_scale)
        finally:
            await cl.prefill_pool.release()
        self.active += 1
        try:
            for i in range(n):
                if context.is_stopped() or context.is_expired():
                    return  # no finished marker: the client migrates
                # the first token is scheduled ahead of the congested batch
                # so TTFT stays a prefill signal and ITL a decode signal
                factor = (1.0 if i == 0 else
                          max(1.0, self.active
                              / max(1, t.decode_seats_per_worker)))
                await asyncio.sleep(t.eff_step * factor * cl.decode_scale)
                if context.is_stopped() or context.is_expired():
                    return
                yield {
                    "token_ids": [1000 + start + i],
                    "finished": i == n - 1,
                    "finish_reason": "length" if i == n - 1 else None,
                    "num_prompt_tokens": start,
                }
        finally:
            self.active -= 1


# ------------------------------- cluster --------------------------------


@dataclass
class SimWorker:
    wid: int
    runtime: DistributedRuntime
    engine: AsyncEngine
    served: object
    component: str


class SimCluster:
    """A live simulated deployment implementing the orchestrator's
    ``WorkerPool``: every worker is a real runtime + ingress server whose
    engine timing comes from the shared load model."""

    def __init__(
        self,
        cfg: RuntimeConfig,
        *,
        namespace: str = "sim",
        prefill_component: str = "prefill",
        decode_component: str = "backend",
        timing: Optional[SimTiming] = None,
        drain_deadline_s: float = 0.15,
        engine_factory: Optional[Callable[[], AsyncEngine]] = None,
    ):
        self.cfg = cfg
        self.namespace = namespace
        self.prefill_component = prefill_component
        self.decode_component = decode_component
        self.timing = timing or SimTiming()
        self.drain_deadline_s = drain_deadline_s
        # when set, spawned workers serve engines from this factory (e.g.
        # real tiny InferenceEngines for the trace-replay scoreboard)
        # instead of the simulated load model
        self.engine_factory = engine_factory
        self.prefill_pool = ResizablePool(1)
        # degradation feedback: cheapened decode steps while clamps hold
        self.decode_scale = 1.0
        self.prefill_scale = 1.0
        self.num_kills = 0
        self._workers: Dict[int, SimWorker] = {}
        self._next_id = 0

    # ------------------------- WorkerPool ---------------------------

    def workers(self, component: str) -> List[int]:
        return sorted(w.wid for w in self._workers.values()
                      if w.component == component)

    def worker_addr(self, worker_id: int) -> str:
        """Advertised ingress address of a live worker — the in-process
        analogue of a live deployment's worker admin URL, so replay code
        can address fault/preempt events at a specific seeded victim."""
        return self._workers[worker_id].served.instance.addr

    async def spawn(self, component: str) -> int:
        rt = await DistributedRuntime.from_settings(self.cfg)
        engine = (self.engine_factory() if self.engine_factory is not None
                  else SimWorkerEngine(self))
        ep = (rt.namespace(self.namespace).component(component)
              .endpoint("generate"))
        served = await ep.serve_endpoint(engine, advertise_host="127.0.0.1")
        wid = self._next_id
        self._next_id += 1
        self._workers[wid] = SimWorker(wid, rt, engine, served, component)
        await self._resize_prefill()
        return wid

    async def stop(self, worker_id: int) -> None:
        sw = self._workers.pop(worker_id)
        await sw.served.drain_and_stop(deadline_s=self.drain_deadline_s)
        await sw.runtime.shutdown()
        await self._stop_engine(sw.engine)
        await self._resize_prefill()

    async def flip(self, worker_id: int, component: str) -> None:
        sw = self._workers[worker_id]
        if sw.component == component:
            return
        # drain off the old role: in-flight joins within the deadline,
        # stragglers are stopped so Migration carries them to a peer
        await sw.served.drain_and_stop(deadline_s=self.drain_deadline_s)
        sw.served.server.draining = False
        ep = (sw.runtime.namespace(self.namespace).component(component)
              .endpoint("generate"))
        sw.served = await ep.serve_endpoint(sw.engine,
                                            advertise_host="127.0.0.1")
        sw.component = component
        await self._resize_prefill()

    # --------------------------- chaos ------------------------------

    async def kill(self, worker_id: int) -> None:
        """Abrupt crash: in-flight streams are cut mid-frame (clients see a
        retryable failure and migrate); the lease revocation deregisters."""
        sw = self._workers.pop(worker_id)
        self.num_kills += 1
        try:
            await sw.served.server.stop()
        except Exception:
            pass
        try:
            await sw.runtime.shutdown()
        except Exception:
            pass
        await self._stop_engine(sw.engine)
        await self._resize_prefill()

    # ------------------------- lifecycle ----------------------------

    async def start(self, n_prefill: int, n_decode: int,
                    batch: int = 16) -> None:
        todo = ([self.prefill_component] * n_prefill
                + [self.decode_component] * n_decode)
        for i in range(0, len(todo), batch):
            await asyncio.gather(*(self.spawn(c) for c in todo[i:i + batch]))

    async def shutdown(self) -> None:
        for sw in list(self._workers.values()):
            try:
                await sw.served.server.stop()
            except Exception:
                pass
            try:
                await sw.runtime.shutdown()
            except Exception:
                pass
            await self._stop_engine(sw.engine)
        self._workers.clear()

    @staticmethod
    async def _stop_engine(engine: AsyncEngine) -> None:
        """Real engines (factory-built) own a decode loop that must stop
        with the worker; the simulated engine has no lifecycle."""
        stop = getattr(engine, "stop", None)
        if stop is None:
            return
        try:
            await stop()
        except Exception:
            pass

    async def _resize_prefill(self) -> None:
        n = len(self.workers(self.prefill_component))
        await self.prefill_pool.resize(
            n * self.timing.prefill_slots_per_worker)

    def apply_degradation(self, actions: dict) -> None:
        """The worker-side effect of the ladder's orders: clamped spec_k
        stops draft-verify amplification, tightened chunking stops long
        prefills stalling decodes — both cheapen decode steps."""
        scale = 1.0
        if actions.get("spec_k_max") is not None:
            scale *= 0.85
        if actions.get("prefill_chunk_tokens_max") is not None:
            scale *= 0.90
        self.decode_scale = scale


# ------------------------------ scenarios -------------------------------


@dataclass
class SimScenario:
    """One closed-loop run: phases (warmup → burst+chaos → cooldown) at
    ``window_s`` planner cadence. All randomness flows from ``seed``."""

    seed: int = 0
    n_prefill: int = 6
    n_decode: int = 10
    timing: SimTiming = field(default_factory=SimTiming)
    isl: int = 32
    osl: int = 8
    base_rps: float = 25.0
    burst_factor: float = 4.0
    diurnal_amplitude: float = 0.15
    diurnal_period_s: float = 4.0
    warmup_s: float = 1.0
    burst_s: float = 2.5
    cooldown_s: float = 2.0
    window_s: float = 0.5
    ttft_sla_s: float = 0.15
    itl_sla_s: float = 0.02
    kill_fraction: float = 0.1
    store_flap_s: float = 0.0  # >0: stop/restart the store mid-burst
    max_chip_budget: int = 32
    min_endpoint: int = 3
    migration_limit: int = 8
    max_concurrency: int = 4096
    max_queue: int = 4096
    tier_weights: Tuple[float, float, float] = (0.3, 0.4, 0.3)
    spec_acceptance: float = 0.62  # synthetic aggregator signal
    attach_aggregator: bool = True
    engage_ratio: float = 1.5  # ladder engagement pressure threshold

    @property
    def duration_s(self) -> float:
        return self.warmup_s + self.burst_s + self.cooldown_s

    def rate(self, t: float) -> float:
        burst = (self.burst_factor
                 if self.warmup_s <= t < self.warmup_s + self.burst_s
                 else 1.0)
        diurnal = 1.0 + self.diurnal_amplitude * math.sin(
            2 * math.pi * t / self.diurnal_period_s)
        return self.base_rps * burst * diurnal


def flagship_scenario(seed: int = 0) -> SimScenario:
    """The 100+ worker configuration (slow; scripts/verify.sh planner).

    Workers are slow enough (eff. 200 ms/step) that ~70 decode replicas are
    genuinely needed at the 45 rps baseline, yet the whole cluster and a 4x
    burst still fit one event loop. The burst's raw demand exceeds the chip
    budget — only degradation (tier shed + clamps) plus scale-to-budget can
    restore the SLO, which is exactly the control story under test."""
    return SimScenario(
        seed=seed,
        n_prefill=32,
        n_decode=72,
        timing=SimTiming(prefill_time_per_token_s=20e-3,
                         decode_time_per_step_s=4.0,
                         speedup_ratio=20.0),
        isl=48,
        osl=6,
        base_rps=45.0,
        warmup_s=2.0,
        burst_s=4.0,
        cooldown_s=4.0,
        window_s=1.0,
        ttft_sla_s=0.6,
        itl_sla_s=0.45,
        store_flap_s=0.4,
        max_chip_budget=150,
        min_endpoint=6,
        max_concurrency=220,
        max_queue=300,
        # the big fleet's overload plateaus nearer the SLA line than the
        # compact scenario's — engage the ladder on a smaller overshoot
        engage_ratio=1.3,
    )


def arrival_times(rng: random.Random, scenario: SimScenario) -> List[float]:
    """Non-homogeneous Poisson arrivals over the scenario's rate curve."""
    out, t = [], 0.0
    while t < scenario.duration_s:
        t += rng.expovariate(max(scenario.rate(t), 1e-6))
        if t < scenario.duration_s:
            out.append(t)
    return out


class _Recorder:
    """Per-window latency reservoirs + run-level per-tier accumulation."""

    RESERVOIR = 4096

    def __init__(self, seed: int):
        self._rng = random.Random(seed)
        self.num_arrived = 0
        self.num_shed = 0
        self.ttft: List[float] = []
        self.itl: List[float] = []
        self.tiers: Dict[int, Dict[str, list]] = {}
        self.request_slo: List[bool] = []  # per-request violation flags

    def _sample(self, samples: list, v: float) -> None:
        if len(samples) < self.RESERVOIR:
            samples.append(v)
        else:
            samples[self._rng.randrange(self.RESERVOIR)] = v

    def record(self, tier: int, ttft_s: float, itls: List[float],
               violated: bool) -> None:
        self._sample(self.ttft, ttft_s)
        for v in itls:
            self._sample(self.itl, v)
        bucket = self.tiers.setdefault(tier, {"ttft": [], "itl": []})
        self._sample(bucket["ttft"], ttft_s)
        for v in itls:
            self._sample(bucket["itl"], v)
        self.request_slo.append(violated)

    def drain_window(self) -> dict:
        win = {
            "num_arrived": self.num_arrived,
            "num_shed": self.num_shed,
            "num_completed": len(self.ttft),
            "ttft_p50_s": percentile(self.ttft, 0.50),
            "ttft_p99_s": percentile(self.ttft, 0.99),
            "itl_p50_s": percentile(self.itl, 0.50),
            "itl_p99_s": percentile(self.itl, 0.99),
        }
        self.num_arrived = 0
        self.num_shed = 0
        self.ttft = []
        self.itl = []
        return win

    def tier_summary(self) -> dict:
        return {
            str(tier): {
                "count": len(b["ttft"]),
                "ttft_p50_s": percentile(b["ttft"], 0.50),
                "ttft_p99_s": percentile(b["ttft"], 0.99),
                "itl_p50_s": percentile(b["itl"], 0.50),
                "itl_p99_s": percentile(b["itl"], 0.99),
            }
            for tier, b in sorted(self.tiers.items())
        }


async def run_scenario(sc: SimScenario, workdir: str) -> dict:
    """Drive the scenario end-to-end with zero manual intervention and
    return the trajectory report. ``workdir`` holds the store snapshot
    (needed for the mid-burst store flap)."""
    rng = random.Random(sc.seed)
    port = _free_port()
    snap = f"{workdir}/sim-store.snap"
    stores = {"live": StoreServer("127.0.0.1", port, persist_path=snap)}
    await stores["live"].start()
    cfg = RuntimeConfig(
        store_addr=f"127.0.0.1:{port}",
        namespace="sim",
        store_reconnect_base_s=0.05,
        store_reconnect_cap_s=0.2,
        store_recover_timeout_s=15.0,
        store_reconcile_grace_s=0.5,
    )
    cluster = SimCluster(cfg, namespace="sim", timing=sc.timing)
    await cluster.start(sc.n_prefill, sc.n_decode)

    front = await DistributedRuntime.from_settings(cfg)
    client = await (front.namespace("sim")
                    .component(cluster.decode_component)
                    .endpoint("generate").client())
    await client.wait_for_instances(sc.n_decode, timeout_s=20.0)
    breakers = CircuitBreakerRegistry(
        BreakerConfig(failure_threshold=3, open_timeout_s=1.0))
    router = KvRouter(
        client, client.endpoint.component,
        block_size=16, use_events=False, seed=0,
        config=KvRouterConfig(replica_sync=False, snapshot_threshold=0),
        breakers=breakers,
    )
    mig = Migration(KvPushRouter(router), migration_limit=sc.migration_limit,
                    backoff_base_s=0.01, rng=random.Random(sc.seed))

    admission = AdmissionController(sc.max_concurrency,
                                    max_queue=sc.max_queue)
    prefill_interp, decode_interp = sc.timing.interpolators()
    connector = VirtualConnector(front.store, namespace="sim")
    planner = Planner(
        PlannerConfig(
            ttft_sla_s=sc.ttft_sla_s,
            itl_sla_s=sc.itl_sla_s,
            adjustment_interval_s=sc.window_s,
            min_endpoint=sc.min_endpoint,
            max_chip_budget=sc.max_chip_budget,
            predictor_order=2,
            degradation=DegradationConfig(engage_ratio=sc.engage_ratio),
        ),
        prefill_interp, decode_interp, connector,
        prefill_component=cluster.prefill_component,
        decode_component=cluster.decode_component,
    )
    orchestrator = Orchestrator(
        front.store, cluster, namespace="sim",
        prefill_component=cluster.prefill_component,
        decode_component=cluster.decode_component,
        max_chip_budget=sc.max_chip_budget,
    )

    def _apply_degradation(actions: dict) -> None:
        admission.min_tier = actions.get("min_tier") or 0
        cluster.apply_degradation(actions)

    watcher = DegradationWatcher(front.store, "sim", _apply_degradation)

    aggregator = None
    if sc.attach_aggregator:
        from ..metrics_aggregator import MetricsAggregator

        aggregator = MetricsAggregator(front, cluster.decode_component)
        await aggregator.start()

    recorder = _Recorder(sc.seed)
    report: dict = {
        "seed": sc.seed, "windows": [], "dropped": [],
        "parity_failures": [], "chaos_window": None,
    }
    expected = [1000 + sc.isl + j for j in range(sc.osl)]
    loop = asyncio.get_running_loop()

    async def _one_request(i: int) -> None:
        tier = rng.choices((0, 1, 2), weights=sc.tier_weights)[0]
        # arrivals (not post-queue admissions) are the demand signal the
        # planner provisions for — a saturated queue must not hide load
        recorder.num_arrived += 1
        try:
            await admission.acquire(tier=tier)
        except AdmissionError:
            recorder.num_shed += 1
            return
        try:
            prompt = [((i * 7 + j) % 500) + 2 for j in range(sc.isl)]
            req = {"token_ids": prompt, "max_tokens": sc.osl}
            t0 = loop.time()
            first = prev = None
            itls: List[float] = []
            toks: List[int] = []
            frames = []
            async for frame in mig.generate(req,
                                            Context(request_id=f"sim-{i}")):
                now = loop.time()
                if first is None:
                    first = now - t0
                else:
                    itls.append(now - prev)
                prev = now
                toks.extend(frame["token_ids"])
                frames.append(frame)
            if (toks != expected or not frames
                    or not frames[-1].get("finished")
                    or any(f["num_prompt_tokens"] != sc.isl for f in frames)):
                recorder.request_slo.append(True)
                report["parity_failures"].append(
                    {"request": i, "tokens": toks})
                return
            mean_itl = sum(itls) / len(itls) if itls else 0.0
            violated = first > sc.ttft_sla_s or mean_itl > sc.itl_sla_s
            recorder.record(tier, first, itls, violated)
        except Exception as exc:
            report["dropped"].append({"request": i, "error": repr(exc)})
        finally:
            admission.release()

    async def _load() -> None:
        t0 = loop.time()
        tasks = []
        for i, at in enumerate(arrival_times(random.Random(sc.seed + 1), sc)):
            delay = t0 + at - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.create_task(_one_request(i)))
        await asyncio.gather(*tasks)

    async def _chaos() -> None:
        # kills land just after the burst begins
        await asyncio.sleep(sc.warmup_s + sc.window_s / 2)
        decode = cluster.workers(cluster.decode_component)
        n_kill = max(1, math.ceil(sc.kill_fraction * len(decode)))
        victims = random.Random(sc.seed + 2).sample(decode, n_kill)
        report["chaos_window"] = len(report["windows"])
        report["killed"] = victims
        for wid in victims:
            await cluster.kill(wid)
        if sc.store_flap_s > 0:
            await asyncio.sleep(sc.window_s)
            await stores["live"].stop()
            await asyncio.sleep(sc.store_flap_s)
            stores["live"] = StoreServer("127.0.0.1", port,
                                         persist_path=snap)
            await stores["live"].start()

    load_task = asyncio.create_task(_load())
    chaos_task = asyncio.create_task(_chaos())

    # ------------- the control loop under test (no human in it) ----------
    n_windows = int(math.ceil(sc.duration_s / sc.window_s)) + 2
    for _w in range(n_windows):
        await asyncio.sleep(sc.window_s)
        win = recorder.drain_window()
        metrics = WindowMetrics(
            num_requests=win["num_arrived"],
            isl_avg=sc.isl, osl_avg=sc.osl,
            ttft_p50_s=win["ttft_p50_s"], ttft_p99_s=win["ttft_p99_s"],
            itl_p50_s=win["itl_p50_s"], itl_p99_s=win["itl_p99_s"],
            ttft_avg_s=win["ttft_p50_s"], itl_avg_s=win["itl_p50_s"],
            # prefill-attributable backlog only: the admission queue is
            # decode pressure and already shows in the ITL correction
            queue_depth=cluster.prefill_pool.waiting,
            breaker_open=sum(1 for s in breakers.states().values()
                             if s != "closed"),
            spec_acceptance=sc.spec_acceptance,
        )
        planner.observe(metrics)
        try:
            await planner.make_adjustments()
            await watcher.poll_once()
            await orchestrator.reconcile()
        except Exception as exc:  # store flap: stale orders, next window wins
            log.warning("control window degraded to staleness: %s", exc)
        win.update({
            "compliant": (
                win["num_completed"] > 0
                and win["ttft_p99_s"] <= sc.ttft_sla_s
                and win["itl_p99_s"] <= sc.itl_sla_s
            ),
            "degradation_level": planner.ladder.level,
            "targets": planner.last_targets,
            "live_prefill": len(cluster.workers(cluster.prefill_component)),
            "live_decode": len(cluster.workers(cluster.decode_component)),
            "breaker_open": metrics.breaker_open,
        })
        report["windows"].append(win)

    await asyncio.wait_for(load_task, timeout=60.0)
    await chaos_task
    if aggregator is not None:
        await asyncio.sleep(0.2)  # let the last planner events land
        report["metrics_text"] = front.metrics.render().decode()
        await aggregator.stop()

    # ------------------------------ report -------------------------------
    # recovery is counted from the first *visible* SLO breach at/after the
    # chaos window (the kill lands mid-window, so the window it falls in may
    # still close compliant) to the first compliant window after it; idle
    # tail windows carry no signal and cannot open a breach
    cw = report["chaos_window"]
    recovery = None
    if cw is not None:
        wins = report["windows"]
        breach = next(
            (i for i in range(cw, len(wins))
             if (wins[i]["num_arrived"] or wins[i]["num_completed"])
             and not wins[i]["compliant"]),
            None,
        )
        if breach is None:
            recovery = 0
        else:
            for idx in range(breach, len(wins)):
                if wins[idx]["compliant"]:
                    recovery = idx - breach
                    break
    report.update({
        "recovery_windows": recovery,
        "num_requests": len(recorder.request_slo),
        "num_shed_total": admission.num_shed,
        "slo_violation_rate": (
            sum(recorder.request_slo) / len(recorder.request_slo)
            if recorder.request_slo else None),
        "tiers": recorder.tier_summary(),
        "degradation_transitions": list(planner.ladder.transitions),
        "degradation_max_level": max(
            (w["degradation_level"] for w in report["windows"]), default=0),
        "orchestrator": {
            "flips": orchestrator.stats.num_flips,
            "spawns": orchestrator.stats.num_spawns,
            "stops": orchestrator.stats.num_stops,
        },
        "num_kills": cluster.num_kills,
    })

    await router.stop()
    await client.stop()
    await front.shutdown()
    await cluster.shutdown()
    await stores["live"].stop()
    return report


# ----------------------- disagg chaos harness ---------------------------
# Real tiny InferenceEngines (CPU JAX) wired exactly like a disaggregated
# P/D worker pair — real kv_inject TCP ingress, real store work queue in
# queue mode — driven through a seeded FaultPlan storm. The invariants it
# certifies are the ones ROADMAP item 3 leans on: byte parity with the
# local-prefill path, zero KV corruption (poisoned-block canary), and zero
# leaked blocks/reservations after the storm.


@dataclass
class DisaggChaosScenario:
    """One seeded disagg chaos run. ``plan_fn(plan)`` installs the fault
    rules; structural events (prefill-worker kill) are fields."""

    name: str
    seed: int = 0
    num_requests: int = 6
    concurrency: int = 2
    use_queue: bool = False
    # hide the device plane from the prefill side → every transfer rides
    # the integrity-checked host relay
    relay_only: bool = False
    prompt_len: Tuple[int, int] = (24, 40)
    max_tokens: int = 6
    queue_wait_s: float = 4.0
    handoff_timeout_s: float = 10.0
    inject_timeout_s: float = 2.0
    transfer_max_retries: int = 3
    retry_backoff_base_s: float = 0.02
    inflight_grace_s: float = 4.0
    min_remote_prefill_tokens: int = 8
    breaker_failure_threshold: int = 100  # storms shouldn't trip by default
    plan_fn: Optional[object] = None      # Callable[[FaultPlan], None]
    # queue mode: hard-kill the queue worker once it pulled this many
    # items (mid-transfer when combined with a disagg.transfer delay)
    kill_prefill_after_pulls: Optional[int] = None
    revive_prefill: bool = True


class _InlinePrefillClient:
    """Push-mode stand-in for the component Client: routes straight to the
    in-process PrefillHandler (transport ingress is still real for the KV
    inject leg, which is the leg the faults target)."""

    def __init__(self, handler):
        self.handler = handler

    def instance_ids(self):
        return [1]

    def round_robin(self, request, context):
        return self.handler.generate(request, Context())


class DisaggChaosHarness:
    """Builds the P/D pair, plants the canary, runs the storm, accounts
    for every block. Use :func:`run_disagg_scenario` for the one-shot
    form."""

    def __init__(self, sc: DisaggChaosScenario):
        self.sc = sc
        self._canary_seq = None
        self._canary_pattern = None
        self._free_baseline: Dict[str, int] = {}

    async def start(self) -> None:
        from ..disagg.handlers import (
            DecodeHandler, DisaggConfig, PrefillHandler, PrefillQueueWorker,
        )
        from ..disagg.ici import DevicePlane
        from ..engine.config import EngineConfig, ModelConfig
        from ..engine.engine import InferenceEngine
        from ..runtime.store import StoreClient
        from ..runtime.transport import IngressServer

        sc = self.sc
        model_cfg = ModelConfig.tiny(vocab_size=256)
        eng_cfg = EngineConfig(
            num_blocks=64, block_size=4, max_model_len=128,
            max_num_batched_tokens=128, prefill_buckets=(128,),
            decode_buckets=(4,), max_num_seqs=4,
        )
        # identical init seeds: the remote-prefill path, the local-fallback
        # path, and the serial reference must all be greedy-identical
        self.prefill_engine = InferenceEngine(model_cfg, eng_cfg, seed=0)
        self.decode_engine = InferenceEngine(model_cfg, eng_cfg, seed=0)
        self.reference_engine = InferenceEngine(model_cfg, eng_cfg, seed=0)

        plane = DevicePlane()
        self.config = DisaggConfig(
            min_remote_prefill_tokens=sc.min_remote_prefill_tokens,
            use_queue=sc.use_queue, queue_name=f"chaos_q_{sc.seed}",
            queue_wait_s=sc.queue_wait_s,
            handoff_timeout_s=sc.handoff_timeout_s,
            inflight_grace_s=sc.inflight_grace_s,
            inject_timeout_s=sc.inject_timeout_s,
            transfer_max_retries=sc.transfer_max_retries,
            retry_backoff_base_s=sc.retry_backoff_base_s,
            breaker_failure_threshold=sc.breaker_failure_threshold,
            orphan_sweep_interval_s=0.5, orphan_grace_s=0.5,
        )
        self.prefill_handler = PrefillHandler(
            self.prefill_engine,
            plane=DevicePlane() if sc.relay_only else plane,
            config=self.config,
        )
        self.store_server = None
        self.queue_worker = None
        self._stores = []
        if sc.use_queue:
            self.store_server = StoreServer(host="127.0.0.1", port=0)
            await self.store_server.start()
            addr = f"127.0.0.1:{self.store_server.port}"
            prefill_store = await StoreClient.connect(addr)
            decode_store = await StoreClient.connect(addr)
            self._stores = [prefill_store, decode_store]
            self.queue_worker = PrefillQueueWorker(
                self.prefill_handler, prefill_store,
                queue_name=self.config.queue_name,
            )
            self.queue_worker.start()
            prefill_client = None
            store = decode_store
        else:
            prefill_client = _InlinePrefillClient(self.prefill_handler)
            store = None
        self.decode_handler = DecodeHandler(
            self.decode_engine, prefill_client=prefill_client,
            config=self.config, plane=plane, store=store,
        )
        self.inject_server = IngressServer(
            self.decode_handler.inject_handler(), host="127.0.0.1", port=0
        )
        await self.inject_server.start()
        self.decode_handler.kv_inject_addr = (
            f"127.0.0.1:{self.inject_server.port}"
        )
        await self._plant_canary()
        self._free_baseline = {
            "prefill": self.prefill_engine.scheduler.pool.num_free,
            "decode": self.decode_engine.scheduler.pool.num_free,
        }

    async def stop(self) -> None:
        if self.queue_worker is not None:
            await self.queue_worker.stop()
        if hasattr(self.prefill_handler, "_transport"):
            await self.prefill_handler._transport.close()
        self.decode_handler.close()
        self.prefill_handler.close()
        await self.inject_server.stop()
        for engine in (self.prefill_engine, self.decode_engine,
                       self.reference_engine):
            await engine.stop()
        for s in self._stores:
            await s.close()
        if self.store_server is not None:
            await self.store_server.stop()

    # ----------------- poisoned-block canary ---------------------------

    async def _plant_canary(self) -> None:
        import numpy as np

        from ..engine.engine import Request

        req = Request(request_id="canary", token_ids=list(range(1, 18)),
                      max_tokens=1)
        seq = self.decode_engine.reserve_sequence(req)
        assert seq is not None, "canary reservation must fit"
        probe = await self.decode_engine.extract_kv_blocks(seq.block_table)
        self._canary_pattern = {
            "k": np.full(probe["k"].shape, 3.0, probe["k"].dtype),
            "v": np.full(probe["v"].shape, -5.0, probe["v"].dtype),
        }
        await self.decode_engine.inject_kv_blocks(
            seq.block_table, self._canary_pattern
        )
        self._canary_seq = seq

    async def _canary_corrupted(self) -> bool:
        import numpy as np

        got = await self.decode_engine.extract_kv_blocks(
            self._canary_seq.block_table
        )
        ok = (np.array_equal(np.asarray(got["k"], np.float32),
                             np.asarray(self._canary_pattern["k"], np.float32))
              and np.array_equal(
                  np.asarray(got["v"], np.float32),
                  np.asarray(self._canary_pattern["v"], np.float32)))
        return not ok

    # ------------------------- the storm --------------------------------

    async def run(self) -> dict:
        from ..runtime import faults
        from ..runtime.faults import FaultPlan

        sc = self.sc
        rng = random.Random(sc.seed)
        prompts = [
            [rng.randrange(1, 255)
             for _ in range(rng.randint(*sc.prompt_len))]
            for _ in range(sc.num_requests)
        ]
        requests = [
            {"token_ids": p, "max_tokens": sc.max_tokens,
             "ignore_eos": True}
            for p in prompts
        ]
        # serial greedy reference BEFORE any fault is installed
        expected = []
        for r in requests:
            expected.append(await self._collect(
                self.reference_engine.generate(dict(r), Context())
            ))

        plan = FaultPlan(seed=sc.seed)
        if sc.plan_fn is not None:
            sc.plan_fn(plan)
        faults.install(plan)
        killer = None
        if sc.kill_prefill_after_pulls is not None:
            killer = asyncio.create_task(self._kill_prefill())
        sem = asyncio.Semaphore(sc.concurrency)
        results: List[Optional[List[int]]] = [None] * sc.num_requests

        async def _one(i: int) -> None:
            async with sem:
                await asyncio.sleep(rng.random() * 0.05)
                try:
                    results[i] = await asyncio.wait_for(
                        self._collect(self.decode_handler.generate(
                            dict(requests[i]),
                            Context(request_id=f"chaos{sc.seed}-{i}"),
                        )),
                        timeout=60.0,
                    )
                except Exception:
                    log.exception("chaos request %d died", i)

        try:
            await asyncio.gather(*(_one(i) for i in range(sc.num_requests)))
        finally:
            faults.clear()
            if killer is not None:
                killer.cancel()
                await asyncio.gather(killer, return_exceptions=True)
        # Quiesce before measuring: stop the queue worker so in-flight
        # prefills receive their cancellations NOW (not at teardown), then
        # wait for sweeps/zombie-reaps to return both pools to baseline.
        # A real leak never converges and still fails the assertion below.
        if self.queue_worker is not None:
            await self.queue_worker.stop()
        for _ in range(50):
            self.decode_handler.sweep_orphans()
            self.prefill_handler.sweep_orphans()
            quiesced = (
                not self.decode_handler.pending
                and not self.prefill_handler._held
                and not self.prefill_engine.scheduler.zombies
                and not self.prefill_engine.scheduler.running
                and (self.prefill_engine.scheduler.pool.num_free
                     == self._free_baseline["prefill"])
                and (self.decode_engine.scheduler.pool.num_free
                     == self._free_baseline["decode"])
            )
            if quiesced:
                break
            await asyncio.sleep(0.2)

        parity_failures = sum(
            1 for got, want in zip(results, expected) if got != want
        )
        completed = sum(1 for got in results if got is not None)
        leaked_pending = (len(self.decode_handler.pending)
                          + len(self.prefill_handler._held))
        # the canary is the only reservation allowed to survive the storm
        leaked_reservations = (
            len(self.decode_engine._kv_reservations)
            - (1 if self._canary_seq is not None else 0)
        )
        canary_corrupted = await self._canary_corrupted()
        leaked_prefill = (self._free_baseline["prefill"]
                          - self.prefill_engine.scheduler.pool.num_free)
        leaked_decode = (self._free_baseline["decode"]
                         - self.decode_engine.scheduler.pool.num_free)
        leaked_blocks = leaked_prefill + leaked_decode
        self.decode_engine.cancel_reservation(self._canary_seq)
        dh, ph = self.decode_handler, self.prefill_handler
        return {
            "name": sc.name,
            "seed": sc.seed,
            "num_requests": sc.num_requests,
            "completed": completed,
            "parity_failures": parity_failures,
            "remote_prefills": dh.num_remote_prefills,
            "local_prefills": dh.num_local_prefills,
            "fallbacks": dh.num_fallbacks,
            "transfer_retries": ph.num_transfer_retries,
            "epoch_rejects": dh.num_epoch_rejects,
            "integrity_rejects": dh.num_integrity_rejects,
            "orphans_reaped": (dh.num_orphans_reaped
                               + ph.num_orphans_reaped),
            "queue_expired": (self.queue_worker.num_expired
                              if self.queue_worker is not None else 0),
            "breaker_trips": dh.fallback_breaker.num_trips,
            "faults_fired": plan.fired(),
            "faults_fired_by_site": plan.fired_counts(),
            "canary_corrupted": canary_corrupted,
            "leaked_blocks": leaked_blocks,
            "leaked_blocks_prefill": leaked_prefill,
            "leaked_blocks_decode": leaked_decode,
            "leaked_pending": leaked_pending,
            "leaked_reservations": leaked_reservations,
        }

    async def _kill_prefill(self) -> None:
        """Hard-kill the queue worker once it pulled enough items (pair
        with a disagg.transfer delay to make the kill land mid-transfer),
        then optionally revive a fresh worker so the storm can recover."""
        from ..disagg.handlers import PrefillQueueWorker

        sc = self.sc
        while (self.queue_worker is None
               or self.queue_worker.num_pulled < sc.kill_prefill_after_pulls):
            await asyncio.sleep(0.01)
        pulled = self.queue_worker.num_pulled
        await self.queue_worker.stop()
        log.info("chaos: killed prefill queue worker after %d pulls", pulled)
        if sc.revive_prefill:
            await asyncio.sleep(0.3)
            self.queue_worker = PrefillQueueWorker(
                self.prefill_handler, self._stores[0],
                queue_name=self.config.queue_name,
            )
            self.queue_worker.start()

    @staticmethod
    async def _collect(stream) -> List[int]:
        toks: List[int] = []
        async for out in stream:
            toks.extend(out["token_ids"])
        return toks


async def run_disagg_scenario(sc: DisaggChaosScenario) -> dict:
    """One-shot: build the harness, run the storm, tear everything down."""
    h = DisaggChaosHarness(sc)
    await h.start()
    try:
        return await h.run()
    finally:
        await h.stop()


# --------------------- preemption chaos harness --------------------------
# Real tiny InferenceEngines driven through seeded preemption storms: a
# maintenance notice lands mid-decode and every in-flight seat must end up
# byte-identical to an unfaulted reference — continued on a peer after a
# device-plane KV hand-off, resumed from the host spill tier, or replayed
# Migration-style from the seat journal. The same harness drives the engine
# stall watchdog (a wedged dispatch window must recover, not hang) and the
# HBM-pressure ladder (spill/pause/shed must engage and release without
# leaking a block).


@dataclass
class PreemptionChaosScenario:
    """One seeded preemption storm. ``mode`` picks the failure shape:

    - ``notice-then-kill``   notice → evacuate to a peer → kill the source
    - ``notice-no-peer``     notice with no peer: spill to the host tier,
                             resume from kvbm prefix hits
    - ``kill-no-notice``     the notice is LOST (fault drop): seats die
                             cold and recovery is Migration-style replay
    - ``stall-mid-window``   a dispatch window wedges on device; the stall
                             watchdog must recover it within the deadline
    - ``pressure-waves``     an undersized pool forces the HBM-pressure
                             ladder through spill → shed and back
    """

    name: str
    mode: str
    seed: int = 0
    num_requests: int = 4
    concurrency: int = 4
    prompt_len: Tuple[int, int] = (24, 40)
    # enough decode runway that seats are still mid-flight when the grace
    # window closes — CPU decode is fast, short budgets drain during it
    max_tokens: int = 20
    # fire the notice once every live request has emitted this many tokens
    notice_after_tokens: int = 2
    # zero grace keeps the storm deterministic: a warmed CPU engine drains
    # any realistic token budget inside a timed grace window, leaving
    # nothing to evacuate (the grace sleep itself has no failure modes)
    notice_grace_s: float = 0.0
    evac_deadline_s: float = 10.0
    # stall-mid-window: watchdog deadline + injected wedge length
    stall_timeout_s: float = 0.4
    stall_delay_s: float = 2.0
    stall_after_windows: int = 3
    # pressure-waves: pool size + ladder thresholds
    pressure_num_blocks: int = 40
    pressure_spill_threshold: float = 0.6
    pressure_shed_threshold: float = 0.85
    plan_fn: Optional[object] = None   # Callable[[FaultPlan], None]


class PreemptionChaosHarness:
    """Builds the source/peer/reference engine trio, plants the canary on
    the receiver, runs the storm, accounts for every block. Use
    :func:`run_preemption_scenario` for the one-shot form."""

    def __init__(self, sc: PreemptionChaosScenario):
        self.sc = sc
        self._canary_seq = None
        self._canary_pattern = None
        self._free_baseline: Dict[str, int] = {}

    # ------------------------------ setup -------------------------------

    async def start(self) -> None:
        from ..engine.config import EngineConfig, ModelConfig
        from ..engine.engine import InferenceEngine
        from ..kvbm.manager import KvbmConfig
        from ..runtime.preemption import PreemptionCoordinator

        sc = self.sc
        model_cfg = ModelConfig.tiny(vocab_size=256)
        kwargs: dict = {}
        num_blocks = 64
        if sc.mode == "stall-mid-window":
            # two decode rungs so quarantine can route 4-row windows to
            # the 8-row bucket instead of rebuilding with einsum attention
            kwargs = {"stall_timeout_s": sc.stall_timeout_s,
                      "stall_seq_retries": 4, "stall_dead_threshold": 10}
        elif sc.mode == "pressure-waves":
            num_blocks = sc.pressure_num_blocks
            kwargs = {
                "pressure_spill_threshold": sc.pressure_spill_threshold,
                "pressure_shed_threshold": sc.pressure_shed_threshold,
                "pressure_release": 0.1,
            }
        eng_cfg = EngineConfig(
            num_blocks=num_blocks, block_size=4, max_model_len=128,
            max_num_batched_tokens=128, prefill_buckets=(128,),
            decode_buckets=(4, 8), max_num_seqs=4, **kwargs,
        )
        ref_cfg = EngineConfig(
            num_blocks=64, block_size=4, max_model_len=128,
            max_num_batched_tokens=128, prefill_buckets=(128,),
            decode_buckets=(4, 8), max_num_seqs=4,
        )
        # identical init seeds: evacuated continuations, spill resumes, and
        # the serial reference must all be greedy-identical
        self.src = InferenceEngine(model_cfg, eng_cfg, seed=0)
        self.peer = InferenceEngine(model_cfg, ref_cfg, seed=0)
        self.reference = InferenceEngine(model_cfg, ref_cfg, seed=0)
        if sc.mode == "notice-no-peer":
            # the spill tier: src evacuates into its host pool; the resume
            # worker onboards from the SAME pool (a shared host tier, as
            # the store remote tier would be in production)
            self.src.attach_kvbm(KvbmConfig(host_blocks=256))
            self.peer.attach_kvbm(KvbmConfig(host_blocks=256))
            self.peer.kvbm.host_pool = self.src.kvbm.host_pool
        self.coordinator = PreemptionCoordinator(
            self.src,
            worker_key=f"chaos-{sc.seed}",
            peer=self.peer if sc.mode == "notice-then-kill" else None,
            notice_grace_s=sc.notice_grace_s,
            evac_deadline_s=sc.evac_deadline_s,
        )
        await self._plant_canary()
        self._free_baseline = {
            "src": self.src.scheduler.pool.num_free,
            "peer": self.peer.scheduler.pool.num_free,
        }

    async def stop(self) -> None:
        for engine in (self.src, self.peer, self.reference):
            await engine.stop()

    async def _plant_canary(self) -> None:
        import numpy as np

        from ..engine.engine import Request

        req = Request(request_id="canary", token_ids=list(range(1, 18)),
                      max_tokens=1)
        seq = self.peer.reserve_sequence(req)
        assert seq is not None, "canary reservation must fit"
        probe = await self.peer.extract_kv_blocks(seq.block_table)
        self._canary_pattern = {
            "k": np.full(probe["k"].shape, 3.0, probe["k"].dtype),
            "v": np.full(probe["v"].shape, -5.0, probe["v"].dtype),
        }
        await self.peer.inject_kv_blocks(seq.block_table,
                                         self._canary_pattern)
        self._canary_seq = seq

    async def _canary_corrupted(self) -> bool:
        import numpy as np

        got = await self.peer.extract_kv_blocks(self._canary_seq.block_table)
        ok = (np.array_equal(np.asarray(got["k"], np.float32),
                             np.asarray(self._canary_pattern["k"],
                                        np.float32))
              and np.array_equal(
                  np.asarray(got["v"], np.float32),
                  np.asarray(self._canary_pattern["v"], np.float32)))
        return not ok

    # ---------------------------- collectors ----------------------------

    @staticmethod
    async def _collect_wire(stream) -> Tuple[List[int], Optional[str]]:
        """Tokens + final finish_reason from a wire-dict stream. Keyed by
        index: an abort/evacuation finish frame re-carries the last token,
        which must not be double-counted."""
        toks: Dict[int, int] = {}
        reason = None
        async for out in stream:
            for t in out["token_ids"]:
                if t >= 0:
                    toks[out["index"]] = t
            if out.get("finished"):
                reason = out.get("finish_reason")
        return [toks[i] for i in sorted(toks)], reason

    @staticmethod
    async def _collect_outputs(aiter) -> Tuple[List[int], Optional[str]]:
        """Same, for a StepOutput stream (submit / resume_prefilled)."""
        toks: Dict[int, int] = {}
        reason = None
        async for out in aiter:
            if out.token_id >= 0:
                toks[out.index] = out.token_id
            if out.finished:
                reason = out.finish_reason
                break
        return [toks[i] for i in sorted(toks)], reason

    # ----------------------------- the storm ----------------------------

    async def run(self) -> dict:
        from ..runtime import faults
        from ..runtime.faults import FaultPlan

        sc = self.sc
        rng = random.Random(sc.seed)
        prompts = [
            [rng.randrange(1, 255)
             for _ in range(rng.randint(*sc.prompt_len))]
            for _ in range(sc.num_requests)
        ]
        requests = [
            {"token_ids": p, "max_tokens": sc.max_tokens,
             "ignore_eos": True}
            for p in prompts
        ]
        # serial greedy reference BEFORE any fault is installed
        expected = []
        for r in requests:
            toks, _ = await self._collect_wire(
                self.reference.generate(dict(r), Context())
            )
            expected.append(toks)

        plan = FaultPlan(seed=sc.seed)
        if sc.plan_fn is not None:
            sc.plan_fn(plan)
        if sc.mode == "kill-no-notice":
            plan.drop_connection("preempt.notice")
        if sc.mode == "stall-mid-window":
            plan.delay("engine.stall", sc.stall_delay_s,
                       after=sc.stall_after_windows, times=1)
        faults.install(plan)

        progress = [0] * sc.num_requests
        results: List[Optional[List[int]]] = [None] * sc.num_requests
        reasons: List[Optional[str]] = [None] * sc.num_requests
        sem = asyncio.Semaphore(sc.concurrency)

        async def _one(i: int) -> None:
            async with sem:
                await asyncio.sleep(rng.random() * 0.02)
                ctx = Context(request_id=f"preempt{sc.seed}-{i}")
                for attempt in range(40):
                    try:
                        toks: Dict[int, int] = {}
                        reason = None
                        async for out in self.src.generate(
                            dict(requests[i]), ctx
                        ):
                            for t in out["token_ids"]:
                                if t >= 0:
                                    toks[out["index"]] = t
                            progress[i] = len(toks)
                            if out.get("finished"):
                                reason = out.get("finish_reason")
                        results[i] = [toks[k] for k in sorted(toks)]
                        reasons[i] = reason
                        return
                    except RuntimeError as exc:
                        # admission shed (pressure rung 3): back off and
                        # retry, exactly what the router would do
                        if "shed" not in str(exc):
                            raise
                        await asyncio.sleep(0.05)
                raise AssertionError(f"request {i} shed forever")

        report = None

        async def _notice_when_decoding() -> None:
            nonlocal report
            while not all(p >= sc.notice_after_tokens or r is not None
                          for p, r in zip(progress, results)):
                await asyncio.sleep(0.005)
            report = await self.coordinator.notice("chaos")
            if sc.mode == "kill-no-notice":
                # the notice was dropped: the kill lands on live seats
                for seq in list(self.src.scheduler.running):
                    self.src.abort(seq.seq_id, "error")

        noticer = None
        if sc.mode in ("notice-then-kill", "notice-no-peer",
                       "kill-no-notice"):
            noticer = asyncio.create_task(_notice_when_decoding())
        try:
            await asyncio.wait_for(
                asyncio.gather(*(_one(i) for i in range(sc.num_requests))),
                timeout=120.0,
            )
            if noticer is not None:
                await asyncio.wait_for(noticer, timeout=30.0)
        finally:
            faults.clear()
            if noticer is not None and not noticer.done():
                noticer.cancel()
                await asyncio.gather(noticer, return_exceptions=True)

        # ----- resume every interrupted seat and splice the tails -----
        by_seat = {}
        if report is not None:
            by_seat = {r.record.seq_id: r for r in report.results}
        spliced: List[Optional[List[int]]] = []
        for i in range(sc.num_requests):
            got, reason = results[i], reasons[i]
            if got is None:
                spliced.append(None)
                continue
            if reason in ("length", "stop"):
                spliced.append(got)        # finished before the storm hit
                continue
            rid = f"preempt{sc.seed}-{i}"
            res = by_seat.get(rid)
            if res is not None and res.mode == "peer":
                # receiver re-emits the frontier token as index 0; the
                # source already delivered it
                tail, _ = await self._collect_outputs(
                    self.peer.resume_prefilled(
                        res.dst_seq, res.record.first_token())
                )
                spliced.append(got + tail[1:])
            elif res is not None and res.mode in ("spill", "fallback"):
                req = res.record.resume_request()
                tail, _ = await self._collect_outputs(
                    await self._submit(self.peer, req))
                spliced.append(got + tail)
            elif reason == "error" and sc.mode == "kill-no-notice":
                # Migration-style replay from client state: full history
                # as prompt, budget shrunk by what was delivered
                from ..engine.engine import Request

                req = Request(
                    request_id=rid, token_ids=list(prompts[i]) + got,
                    max_tokens=max(1, sc.max_tokens - len(got)),
                    ignore_eos=True,
                )
                tail, _ = await self._collect_outputs(
                    await self._submit(self.peer, req))
                spliced.append(got + tail)
            else:
                spliced.append(got)

        # quiesce: all seats finished, pools back to baseline
        for _ in range(50):
            if (not self.src.scheduler.running
                    and not self.src.scheduler.waiting
                    and not self.peer.scheduler.running
                    and (self.src.scheduler.pool.num_free
                         == self._free_baseline["src"])
                    and (self.peer.scheduler.pool.num_free
                         == self._free_baseline["peer"])):
                break
            await asyncio.sleep(0.2)

        parity_failures = sum(
            1 for got, want in zip(spliced, expected) if got != want
        )
        completed = sum(1 for got in spliced if got is not None)
        leaked_src = (self._free_baseline["src"]
                      - self.src.scheduler.pool.num_free)
        leaked_peer = (self._free_baseline["peer"]
                       - self.peer.scheduler.pool.num_free)
        # the canary is the only reservation allowed to survive the storm
        leaked_reservations = (
            len(self.src._kv_reservations)
            + len(self.peer._kv_reservations)
            - (1 if self._canary_seq is not None else 0)
        )
        leaked_pending = sum(
            s.pending_total for s in self.src.scheduler.running
        )
        canary_corrupted = await self._canary_corrupted()
        self.peer.cancel_reservation(self._canary_seq)
        out = {
            "name": sc.name,
            "mode": sc.mode,
            "seed": sc.seed,
            "num_requests": sc.num_requests,
            "completed": completed,
            "parity_failures": parity_failures,
            "notices": self.coordinator.num_notices,
            "evacuated_peer": self.coordinator.num_evacuated,
            "spilled": self.coordinator.num_spilled,
            "fallbacks": self.coordinator.num_fallbacks,
            "journal_len": len(self.coordinator.journal),
            "notice_lost": bool(report.notice_lost) if report else False,
            "deadline_blown": (bool(report.deadline_blown)
                               if report else False),
            "stalls": self.src.num_stalls,
            "stall_dead": self.src.stall_dead,
            "quarantined_shapes": len(self.src._shape_quarantine),
            "pressure_spills": self.src.num_pressure_spills,
            "pressure_shed": self.src.num_pressure_shed,
            "pressure_level": self.src.pressure_level,
            "pressure_peak": self.src.pressure_peak,
            "onboarded_blocks": (
                self.peer.kvbm.stats.onboarded_blocks
                if self.peer.kvbm is not None else 0),
            "faults_fired": plan.fired(),
            "faults_fired_by_site": plan.fired_counts(),
            "canary_corrupted": canary_corrupted,
            "leaked_blocks": leaked_src + leaked_peer,
            "leaked_blocks_src": leaked_src,
            "leaked_blocks_peer": leaked_peer,
            "leaked_pending": leaked_pending,
            "leaked_reservations": leaked_reservations,
        }
        return out

    @staticmethod
    async def _submit(engine, req):
        return engine.submit(req)


async def run_preemption_scenario(sc: PreemptionChaosScenario) -> dict:
    """One-shot: build the harness, run the storm, tear everything down."""
    h = PreemptionChaosHarness(sc)
    await h.start()
    try:
        return await h.run()
    finally:
        await h.stop()
