"""Mocker worker process: a device-free engine on the cluster
(ref: components/backends/mocker/src/dynamo/mocker/main.py).

    python -m dynamo_tpu.mocker --model-name mock --tokenizer tok.json \
        --speedup-ratio 10

Registers and serves exactly like a real worker — frontends, routers, and
the planner cannot tell the difference, which is the point: multi-worker
routing/overload/fault scenarios run in CI without a TPU.
"""

from __future__ import annotations

import argparse
import asyncio

from ..engine.config import EngineConfig
from ..runtime.component import DistributedRuntime
from ..serving import ServeOptions, load_tokenizer, run_until_shutdown, serve_engine
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger
from .engine import MockEngine, MockerConfig

log = get_logger("mocker")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu mocker worker")
    p.add_argument("--model-name", default="mock")
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=2048)
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-batched-tokens", type=int, default=512)
    p.add_argument("--max-model-len", type=int, default=8192)
    p.add_argument("--speedup-ratio", type=float, default=1.0)
    p.add_argument("--migration-limit", type=int, default=3)
    p.add_argument("--advertise-host", default="127.0.0.1")
    return p.parse_args(argv)


async def run_mocker(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace

    eng_cfg = EngineConfig(
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_num_seqs=args.max_num_seqs,
        max_num_batched_tokens=args.max_batched_tokens,
        max_model_len=args.max_model_len,
    )
    tokenizer = load_tokenizer(args.tokenizer)
    # sample inside the real vocab so mock tokens always detokenize
    vocab = tokenizer.vocab_size if tokenizer is not None else 512
    engine = MockEngine(
        eng_cfg,
        MockerConfig(vocab_size=vocab, speedup_ratio=args.speedup_ratio),
    )
    runtime = await DistributedRuntime.from_settings(config)
    opts = ServeOptions(
        name=args.model_name, component=args.component,
        endpoint=args.endpoint, advertise_host=args.advertise_host,
        migration_limit=args.migration_limit,
    )
    served, kv_pub, metrics_pub = await serve_engine(
        runtime, engine, eng_cfg, opts, tokenizer
    )
    log.info("mocker ready: model=%s speedup=%.1f",
             args.model_name, args.speedup_ratio)
    await run_until_shutdown(runtime, engine, served, kv_pub, metrics_pub)


def main(argv=None) -> None:
    asyncio.run(run_mocker(parse_args(argv)))


if __name__ == "__main__":
    main()
