"""Mock engine: real scheduler, simulated device time
(ref: lib/llm/src/mocker/{engine,scheduler,kv_manager}.rs — the reference
rebuilds vLLM scheduling semantics for its mocker; ours *is* the production
scheduler, so the simulation can't drift from the real engine).

Timing model: a step that prefills P tokens and decodes a batch of D
sequences costs

    dt = (P · prefill_time_per_token + [D>0] · decode_time_per_step
          + D · decode_time_per_token) / speedup_ratio

which captures the two TPU regimes — prefill is compute-bound (cost ∝
tokens), decode is launch/HBM-bound (flat per step + small per-seq term).
Sampled tokens are deterministic xxh3 draws so runs are reproducible.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass
from typing import List, Tuple

import xxhash

from ..engine.config import EngineConfig
from ..engine.engine import EngineCore


@dataclass
class MockerConfig:
    """Timing + shape knobs for the simulated device."""

    vocab_size: int = 512
    prefill_time_per_token_s: float = 50e-6   # ~20k tok/s prefill
    decode_time_per_step_s: float = 5e-3      # flat step launch cost
    decode_time_per_token_s: float = 50e-6
    speedup_ratio: float = 1.0                # >1 accelerates simulated time


class MockEngine(EngineCore):
    """Drop-in AsyncEngine with no device behind it."""

    def __init__(self, engine_config: EngineConfig,
                 mock_config: MockerConfig | None = None):
        super().__init__(engine_config)
        self.mock = mock_config or MockerConfig()

    def _sample(self, seq_id: str, position: int) -> int:
        """Deterministic pseudo-random token; avoids ids < 4 so reserved
        specials (pad/bos/eos) are never emitted and generation runs to
        max_tokens unless the prompt's own eos ids say otherwise."""
        h = xxhash.xxh3_64_intdigest(
            seq_id.encode() + struct.pack("<I", position), seed=7
        )
        lo = min(4, self.mock.vocab_size - 1)
        return lo + h % max(1, self.mock.vocab_size - lo)

    async def _execute_batch_async(self, batch) -> Tuple[List[int], List[int]]:
        m = self.mock
        prefill_tokens = sum(c.length for c in batch.prefills)
        dt = prefill_tokens * m.prefill_time_per_token_s
        if batch.decodes:
            dt += (m.decode_time_per_step_s
                   + len(batch.decodes) * m.decode_time_per_token_s)
        if dt > 0:
            await asyncio.sleep(dt / m.speedup_ratio)
        prefill_samples = [
            self._sample(c.seq.seq_id, c.seq.total_tokens)
            for c in batch.prefills
        ]
        decode_samples = [
            self._sample(s.seq_id, s.total_tokens) for s in batch.decodes
        ]
        return prefill_samples, decode_samples
