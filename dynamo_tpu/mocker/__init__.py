"""Device-free mock engine (ref: lib/llm/src/mocker/engine.rs:48).

A faithful vLLM-semantics simulator: reuses the REAL continuous-batching
scheduler and paged block pool (``dynamo_tpu.engine.scheduler``) — so prefix
caching, eviction, watermark admission, and preemption behave identically to
the production engine — but replaces device execution with a timing model
(``speedup_ratio`` accelerates simulated time). Publishes real KV events and
scheduler stats, making router/planner e2e tests possible without TPUs.
"""

from .engine import MockEngine, MockerConfig

__all__ = ["MockEngine", "MockerConfig"]
