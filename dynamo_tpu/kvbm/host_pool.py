"""G2 host-memory + G3 disk block pools, sequence-hash keyed
(ref: lib/llm/src/block_manager/pool/managed.rs — active/inactive pools
with hash reuse; storage/disk.rs for the disk tier).

A block's payload is its per-block KV: ``{"k","v"}: [L, KV, bs, hd]``
numpy arrays — plus ``"ks"``/``"vs"`` float32 scales when the engine
serves a quantized KV cache. G2 is an LRU dict bounded by
``capacity_blocks`` AND (when ``capacity_bytes`` > 0) by total payload
bytes — the byte bound is what lets an int8 cache hold ~2x the blocks of
a bf16 cache in the same host budget. Overflow spills to G3 (one file per
block under ``disk_dir``) when configured, else drops. Lookups check G2
then G3 (disk hits are re-promoted to G2).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from ..utils.logging import get_logger

log = get_logger("kvbm.host_pool")


def _restore_dtype(name: str) -> np.dtype:
    """Resolve a saved dtype name, reaching into ml_dtypes for the
    numpy-foreign ones (bfloat16, float8_e4m3fn, ...)."""
    import ml_dtypes

    return np.dtype(getattr(ml_dtypes, name, name))


@dataclass
class HostPoolStats:
    g2_blocks: int = 0
    g2_bytes: int = 0
    g3_blocks: int = 0
    g2_hits: int = 0
    g3_hits: int = 0
    misses: int = 0
    spills: int = 0
    drops: int = 0


class HostBlockPool:
    def __init__(
        self,
        capacity_blocks: int,
        disk_dir: Optional[str] = None,
        disk_capacity_blocks: int = 0,
        capacity_bytes: int = 0,
    ):
        self.capacity = capacity_blocks
        # 0 = unbounded; rides the incremental _mem_bytes accounting, so
        # the bound is O(1) per put regardless of pool size
        self.capacity_bytes = capacity_bytes
        self.disk_dir = Path(disk_dir) if disk_dir else None
        self.disk_capacity = disk_capacity_blocks if disk_dir else 0
        if self.disk_dir is not None:
            self.disk_dir.mkdir(parents=True, exist_ok=True)
        self._mem: "OrderedDict[int, Dict[str, np.ndarray]]" = OrderedDict()
        self._mem_bytes = 0  # incremental: a per-put sum over G2 is O(n)
        self._disk: "OrderedDict[int, Path]" = OrderedDict()
        self.stats = HostPoolStats()
        # called with a seq_hash that left the pool entirely (distributed
        # KVBM retracts its presence advertisement)
        self.on_drop = None

    # -- query --

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._mem or seq_hash in self._disk

    def get(self, seq_hash: int) -> Optional[Dict[str, np.ndarray]]:
        data = self._mem.get(seq_hash)
        if data is not None:
            self._mem.move_to_end(seq_hash)
            self.stats.g2_hits += 1
            return data
        path = self._disk.get(seq_hash)
        if path is not None:
            try:
                with np.load(path) as z:
                    if "__keys__" in z:
                        # per-key payload + dtype (quantized caches mix
                        # 1-byte pages with float32 scales)
                        data = {}
                        for key in [str(x) for x in z["__keys__"]]:
                            a = z[key]
                            dtype = str(z[f"{key}_dtype"])
                            if dtype != a.dtype.name:
                                a = a.view(_restore_dtype(dtype))
                            data[key] = a
                    else:  # legacy {"k","v"} single-dtype layout
                        data = {"k": z["k"], "v": z["v"]}
                        # bfloat16 round-trips as uint16 views (np.savez
                        # can't serialise ml_dtypes natively)
                        dtype = str(z["dtype"]) if "dtype" in z else None
                        if dtype and dtype != data["k"].dtype.name:
                            dt = _restore_dtype(dtype)
                            data = {n: a.view(dt) for n, a in data.items()}
            except Exception:
                log.exception("G3 read failed for %x", seq_hash)
                self._disk.pop(seq_hash, None)
                return None
            self.stats.g3_hits += 1
            self.put(seq_hash, data)  # promote back to G2
            return data
        self.stats.misses += 1
        return None

    # -- insert --

    def put(self, seq_hash: int, data: Dict[str, np.ndarray]) -> None:
        if seq_hash in self._mem:
            self._mem.move_to_end(seq_hash)
            return
        self._mem[seq_hash] = data
        self._mem_bytes += sum(a.nbytes for a in data.values())
        while self._mem and (
            len(self._mem) > self.capacity
            or (self.capacity_bytes > 0
                and self._mem_bytes > self.capacity_bytes)
        ):
            old_hash, old_data = self._mem.popitem(last=False)
            self._mem_bytes -= sum(a.nbytes for a in old_data.values())
            self._spill(old_hash, old_data)
        self._refresh()

    def _spill(self, seq_hash: int, data: Dict[str, np.ndarray]) -> None:
        if self.disk_dir is None or self.disk_capacity <= 0:
            self.stats.drops += 1
            if self.on_drop is not None:
                self.on_drop(seq_hash)
            return
        if seq_hash in self._disk:
            return
        path = self.disk_dir / f"{seq_hash:016x}.npz"
        try:
            save: Dict[str, np.ndarray] = {
                "__keys__": np.asarray(sorted(data.keys()))
            }
            for key, a in data.items():
                save[f"{key}_dtype"] = np.asarray(a.dtype.name)
                if a.dtype.kind not in "fiu":  # ml_dtypes (bf16, fp8 ...)
                    a = a.view(np.uint16 if a.dtype.itemsize == 2
                               else np.uint8)
                save[key] = a
            np.savez(path, **save)
        except Exception:
            log.exception("G3 spill failed for %x", seq_hash)
            if self.on_drop is not None:  # the block is gone — retract
                self.on_drop(seq_hash)
            return
        self._disk[seq_hash] = path
        self.stats.spills += 1
        while len(self._disk) > self.disk_capacity:
            old_hash, old_path = self._disk.popitem(last=False)
            try:
                os.unlink(old_path)
            except OSError:
                pass
            # fire only when the block left the pool ENTIRELY — a G3 copy
            # of a block promoted back to G2 stays servable from _mem
            if self.on_drop is not None and old_hash not in self._mem:
                self.on_drop(old_hash)
        self._refresh()

    def _refresh(self) -> None:
        self.stats.g2_blocks = len(self._mem)
        self.stats.g2_bytes = self._mem_bytes
        self.stats.g3_blocks = len(self._disk)
