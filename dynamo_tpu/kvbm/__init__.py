"""Multi-tier KV block manager (KVBM)
(ref: lib/llm/src/block_manager/ — G1 device / G2 pinned-host / G3 disk
pools, offload manager, sequence-hash reuse).

TPU-first redesign: G1 *is* the engine's paged-cache block pool, so the
"device pool" needs no second implementation. Sealed blocks are offloaded
write-through (batched async gathers between steps — never an
extract-on-evict stall inside the scheduler), and onboarding promotes host
blocks back into the G1 prefix cache, so the scheduler's existing prefix
matching serves G2/G3 hits with zero changes to the hot path.
"""

from .host_pool import HostBlockPool
from .manager import KvbmConfig, KvbmManager

__all__ = ["HostBlockPool", "KvbmConfig", "KvbmManager"]
