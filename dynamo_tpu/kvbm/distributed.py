"""Distributed KVBM: cluster-shared G2 host tier over the store + direct
TCP block fetch (ref: lib/llm/src/block_manager/distributed/leader.rs:126,
worker.rs:133 — the reference forms a leader/worker group over ZMQ and
moves blocks with NIXL; here group bring-up rides the store barrier and the
data plane is the same TCP transport the disagg KV push uses).

Three pieces:

- :class:`KvbmGroup` — leader/worker bring-up: the leader publishes the
  group's block-layout contract (block_size, num_layers, kv heads, head
  dim, dtype) through the barrier; joining workers must match it exactly,
  because a mismatched layout would scatter garbage into the paged cache.
- presence plane: after offloading a block to local G2, a worker writes
  ``kvbm/g2/{ns}/{seq_hash}/{worker_id} → {addr}`` under its primary lease
  (worker death erases its claims automatically).
- data plane: each worker serves a ``kvbm_fetch`` TCP endpoint returning
  requested blocks from its host pool; peers fetch on onboard miss and
  lazily delete presence keys that turn out stale (evicted from the
  holder's G2 between publish and fetch).
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Any, Dict, Iterable, List, Optional

import msgpack

from ..disagg.protocol import kv_from_wire, kv_to_wire
from ..runtime.barrier import LeaderBarrier, WorkerBarrier
from ..runtime.context import Context
from ..runtime.engine import FnEngine
from ..runtime.transport import IngressServer, TransportClient
from ..utils.logging import get_logger

log = get_logger("kvbm.dist")


def engine_layout(engine) -> dict:
    """The block-layout contract two engines must share to exchange KV."""
    m, e = engine.model_config, engine.config
    return {
        "block_size": e.block_size,
        "num_layers": m.num_layers,
        "num_kv_heads": m.num_kv_heads,
        "head_dim": m.head_dim_,
        "dtype": m.dtype,
    }


class KvbmGroup:
    """Leader/worker group formation (ref: distributed/leader.rs:126)."""

    @staticmethod
    async def lead(store, name: str, num_workers: int, layout: dict,
                   timeout_s: float = 120.0) -> list:
        """Leader side: publish the layout, wait for every worker."""
        return await LeaderBarrier(
            f"kvbm/{name}", num_workers, timeout_s=timeout_s
        ).sync(store, layout)

    @staticmethod
    async def join(store, name: str, worker_name: str, layout: dict,
                   timeout_s: float = 120.0) -> dict:
        """Worker side: validate layout compatibility BEFORE checking in —
        posting the barrier key first would satisfy the leader's count and
        let it report a 'formed' group missing this worker."""
        from ..runtime.component import BARRIER_ROOT

        [(_k, raw)] = await store.wait_for_key_count(
            f"{BARRIER_ROOT}kvbm/{name}/data", 1, timeout_s=timeout_s
        )
        leader_layout = msgpack.unpackb(raw, raw=False)
        if leader_layout != layout:
            raise RuntimeError(
                f"KVBM layout mismatch: leader {leader_layout} != "
                f"worker {layout} — cross-host KV transfer would corrupt "
                f"the paged cache"
            )
        return await WorkerBarrier(
            f"kvbm/{name}", worker_name, timeout_s=timeout_s
        ).sync(store, layout)


class DistributedKvbm:
    """Peer-G2 plane for one worker: presence publishing + block serving +
    onboard-time peer fetch. Attach with ``manager.peers = this`` (or pass
    ``distributed=`` to :func:`attach`)."""

    PREFIX = "kvbm/g2/"

    def __init__(self, manager, store, worker_id: int,
                 namespace: str = "dynamo",
                 advertise_host: str = "127.0.0.1",
                 scope: Optional[str] = None):
        self.manager = manager
        self.store = store
        self.worker_id = worker_id
        # the presence prefix embeds a fingerprint of (scope, layout):
        # workers serving a different model or block layout simply never
        # see each other's keys — token-based seq hashes collide across
        # models, and a foreign-model block with the right shape would be
        # silently-wrong KV (the barrier check alone is opt-in)
        layout = engine_layout(manager.engine)
        digest = hashlib.sha1(msgpack.packb(
            {"scope": scope or "", **layout}
        )).hexdigest()[:12]
        self.prefix = f"{self.PREFIX}{namespace}/{digest}/"
        self.advertise_host = advertise_host
        self.addr: Optional[str] = None
        self._server: Optional[IngressServer] = None
        self._transport: Optional[TransportClient] = None
        self._dropped: List[int] = []  # evicted hashes pending unpublish
        self.num_published = 0
        self.num_unpublished = 0
        self.num_served = 0
        self.num_peer_hits = 0
        self.num_stale_keys = 0

    # ------------------------- lifecycle -------------------------------

    async def start(self) -> None:
        self._server = IngressServer(
            FnEngine(self._serve_fetch), host="0.0.0.0", port=0
        )
        await self._server.start()
        self.addr = f"{self.advertise_host}:{self._server.port}"
        self._transport = TransportClient()
        self.manager.peers = self
        # G2 eviction must retract the advertisement, or stale keys grow
        # with total offloads instead of G2 capacity
        self.manager.host_pool.on_drop = self._dropped.append
        log.info("distributed KVBM serving G2 fetch at %s", self.addr)

    async def stop(self) -> None:
        if self.manager.peers is self:
            self.manager.peers = None
        if self.manager.host_pool.on_drop == self._dropped.append:
            self.manager.host_pool.on_drop = None
        if self._transport is not None:
            await self._transport.close()
            self._transport = None
        if self._server is not None:
            await self._server.stop()
            self._server = None

    # ------------------------- data plane ------------------------------

    async def _serve_fetch(self, request: Any, context: Context):
        """Peer ingress: return requested blocks from the local host pool."""
        blocks: Dict[str, dict] = {}
        for h in request.get("seq_hashes", ()):
            data = self.manager.host_pool.get(int(h))
            if data is not None:
                blocks[f"{int(h):016x}"] = kv_to_wire(data)
        self.num_served += len(blocks)
        yield {"blocks": blocks}

    def _key(self, seq_hash: int) -> str:
        return f"{self.prefix}{seq_hash:016x}/{self.worker_id}"

    async def publish(self, seq_hash: int) -> None:
        """Advertise one locally-held G2 block (leased: dies with us)."""
        await self.publish_many([seq_hash])

    async def publish_many(self, seq_hashes: Iterable[int]) -> None:
        """Batch-advertise (independent small writes, issued concurrently)
        and retract advertisements for blocks G2 has since dropped.

        Pool membership at publish time is the single source of truth: a
        hash can appear in both lists (evicted then re-offloaded, or
        evicted mid-tick by a later batch member), and a concurrent
        put+delete of the same key would race."""
        payload = msgpack.packb({"addr": self.addr})
        dropped, self._dropped = self._dropped, []
        pool = self.manager.host_pool
        put_hashes = {h for h in seq_hashes if h in pool}
        drop_hashes = {h for h in dropped if h not in pool} - put_hashes
        puts = [
            self.store.put(self._key(h), payload,
                           lease=self.store.primary_lease)
            for h in put_hashes
        ]
        deletes = [self.store.delete(self._key(h)) for h in drop_hashes]
        results = await asyncio.gather(*puts, *deletes,
                                       return_exceptions=True)
        for r in results:
            if isinstance(r, Exception):
                log.warning("presence update failed: %s", r)
        self.num_published += len(puts)
        self.num_unpublished += len(deletes)

    async def fetch(self, seq_hash: int) -> Optional[Dict[str, Any]]:
        """Fetch one block from any peer that advertises it."""
        return (await self.fetch_many([seq_hash])).get(seq_hash)

    async def fetch_many(
        self, seq_hashes: List[int]
    ) -> Dict[int, Dict[str, Any]]:
        """Resolve presence for every hash concurrently, then fetch one
        per-peer batch over TCP (not one round-trip per block). Stale
        advertisements discovered along the way are deleted."""
        if not seq_hashes:
            return {}
        lookups = await asyncio.gather(
            *(self.store.get_prefix(f"{self.prefix}{h:016x}/")
              for h in seq_hashes),
            return_exceptions=True,
        )
        by_addr: Dict[str, List[int]] = {}
        key_of: Dict[tuple, str] = {}
        for h, kvs in zip(seq_hashes, lookups):
            if isinstance(kvs, Exception):
                continue
            for key, value in kvs:
                try:
                    addr = msgpack.unpackb(value, raw=False)["addr"]
                except Exception:
                    continue
                if addr == self.addr:
                    continue  # our own claim
                by_addr.setdefault(addr, []).append(h)
                key_of[(addr, h)] = key
                break  # first live peer is enough
        out: Dict[int, Dict[str, Any]] = {}
        for addr, hs in by_addr.items():
            try:
                async for resp in self._transport.generate(
                    addr, {"seq_hashes": hs}, Context()
                ):
                    blocks = resp.get("blocks", {})
                    for h in hs:
                        block = blocks.get(f"{h:016x}")
                        if block is not None:
                            self.num_peer_hits += 1
                            out[h] = kv_from_wire(block)
                        else:
                            # the peer evicted it — drop the stale key
                            self.num_stale_keys += 1
                            await self.store.delete(key_of[(addr, h)])
                    break
            except Exception:
                log.warning("peer G2 fetch from %s failed", addr,
                            exc_info=True)
        return out
