"""KVBM manager: write-through offload + prefix-cache onboarding
(ref: lib/llm/src/block_manager/offload.rs — priority-queued offload with
transfer batching; block_manager.rs:99 ``KvBlockManager``).

Lifecycle per block:

  sealed in G1 ──(pending queue)──► batched gather → G2 host pool ─► G3 disk
  evicted from G1, prompt needs it ──► adopt G1 block + batched scatter ◄──┘

Offload runs in ``tick()``, called by the engine's step loop *between*
steps: candidate hashes accumulate as the scheduler seals blocks, and one
batched device gather copies up to ``max_offload_per_tick`` blocks per tick.
Removed/cleared pool events invalidate pending candidates before each
snapshot, so a gather never reads a recycled block (both run on the event
loop; device work serialises on the engine's single step executor).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..tokens import SequenceHash
from ..utils.logging import get_logger
from .host_pool import HostBlockPool

log = get_logger("kvbm")


@dataclass
class KvbmConfig:
    host_blocks: int = 1024          # G2 capacity (blocks)
    host_bytes: int = 0              # G2 capacity (bytes; 0 = unbounded) —
    # a byte bound sized to the host budget lets a quantized KV cache
    # (int8/fp8, ~half the bytes per block) hold ~2x the blocks
    disk_dir: Optional[str] = None   # G3 location (None = no disk tier)
    disk_blocks: int = 0             # G3 capacity
    max_offload_per_tick: int = 32   # device-gather batch bound
    max_onboard_blocks: int = 512    # per-request onboard bound


@dataclass
class KvbmStats:
    offloaded_blocks: int = 0
    onboarded_blocks: int = 0
    onboard_requests: int = 0
    invalidated_pending: int = 0
    g4_puts: int = 0
    g4_hits: int = 0
    peer_hits: int = 0      # blocks onboarded from a peer worker's G2


class StoreRemoteTier:
    """G4: cluster-shared KV blocks in the store (ref: block_manager
    CacheLevel::G4 remote tier, block_manager.rs:62-76 — the reference
    backs it with NIXL-addressable object storage; here the lease-KV
    store's value plane). Write-through from the offload tick; any worker
    can onboard another worker's blocks."""

    KEY_PREFIX = "kvbm/g4/"

    def __init__(self, store, namespace: str = "dynamo"):
        self.store = store
        self.prefix = f"{self.KEY_PREFIX}{namespace}/"

    def _key(self, seq_hash: int) -> str:
        return f"{self.prefix}{seq_hash:016x}"

    async def put(self, seq_hash: int, data: Dict[str, np.ndarray]) -> None:
        import msgpack

        from ..disagg.protocol import kv_to_wire

        await self.store.put(
            self._key(seq_hash), msgpack.packb(kv_to_wire(data))
        )

    async def get(self, seq_hash: int) -> Optional[Dict[str, np.ndarray]]:
        import msgpack

        from ..disagg.protocol import kv_from_wire

        raw = await self.store.get(self._key(seq_hash))
        if raw is None:
            return None
        return kv_from_wire(msgpack.unpackb(raw, raw=False))


@dataclass
class _Pending:
    seq_hash: int
    block_hash: int
    parent: Optional[int]
    block_id: int


class KvbmManager:
    """Attached to an :class:`InferenceEngine` via ``attach_kvbm``."""

    # class-level default so partially-constructed fakes stay
    # forward-compatible as attach-time collaborators are added
    prefix = None      # radix prefix manager (prefix.manager)

    def __init__(self, engine, config: Optional[KvbmConfig] = None,
                 remote: Optional[StoreRemoteTier] = None):
        self.engine = engine
        self.config = config or KvbmConfig()
        self.host_pool = HostBlockPool(
            self.config.host_blocks, self.config.disk_dir,
            self.config.disk_blocks,
            capacity_bytes=self.config.host_bytes,
        )
        self.remote = remote   # G4 tier (None = disabled)
        self.peers = None      # distributed peer-G2 plane (kvbm.distributed)
        self.prefix = None     # radix prefix manager (prefix.manager)
        self.stats = KvbmStats()
        # seq_hash -> candidate awaiting offload; insertion-ordered
        self._pending: Dict[int, _Pending] = {}
        self.block_size = engine.config.block_size

    def snapshot(self) -> Dict[str, float]:
        """Scalar wire dict for the worker metrics publisher (the
        aggregator re-exports these as ``kvbm_*`` gauges)."""
        hs = self.host_pool.stats
        out = {
            "host_pool_blocks": hs.g2_blocks + hs.g3_blocks,
            "host_pool_bytes": hs.g2_bytes,
            "spills_total": hs.spills,
            "drops_total": hs.drops,
            "offloaded_total": self.stats.offloaded_blocks,
            "onboarded_total": self.stats.onboarded_blocks,
            "onboard_requests_total": self.stats.onboard_requests,
            "g4_puts_total": self.stats.g4_puts,
            "g4_hits_total": self.stats.g4_hits,
            "peer_hits_total": self.stats.peer_hits,
            # radix prefix index counters (zero while no prefix cache
            # manager is attached — the aggregator zero-defaults them
            # the same way for old workers on the wire)
            "prefix_nodes": 0.0,
            "prefix_hit_tokens_total": 0.0,
            "prefix_evictions_total": 0.0,
        }
        if self.prefix is not None:
            px = self.prefix.snapshot()
            out["prefix_nodes"] = px["prefix_nodes"]
            out["prefix_hit_tokens_total"] = px["prefix_hit_tokens_total"]
            out["prefix_evictions_total"] = px["prefix_evictions_total"]
        return out

    # ---- pool event hook (called synchronously from the scheduler) ----

    def on_pool_event(self, event) -> None:
        if event.kind == "stored":
            for b in event.blocks:
                h = b["seq_hash"]
                if h not in self.host_pool and h not in self._pending:
                    self._pending[h] = _Pending(
                        seq_hash=h,
                        block_hash=b.get("block_hash", h),
                        parent=b.get("parent"),
                        block_id=b["block_id"],
                    )
        elif event.kind == "removed":
            for h in event.blocks:
                if self._pending.pop(h, None) is not None:
                    self.stats.invalidated_pending += 1
        elif event.kind == "cleared":
            self.stats.invalidated_pending += len(self._pending)
            self._pending.clear()

    # ------------------------- offload tick ----------------------------

    async def tick(self) -> int:
        """Offload up to ``max_offload_per_tick`` pending blocks in ONE
        batched device gather. Returns blocks offloaded."""
        if not self._pending:
            return 0
        batch: List[_Pending] = []
        for h in list(self._pending):
            batch.append(self._pending.pop(h))
            if len(batch) >= self.config.max_offload_per_tick:
                break
        block_ids = [p.block_id for p in batch]
        data = await self.engine.extract_kv_blocks(block_ids)
        for i, p in enumerate(batch):
            # copy each [L, KV, bs, hd] block out of the batched gather —
            # a numpy view would pin the whole batch buffer in G2.  A
            # quantized cache adds "ks"/"vs" scale tensors to the payload.
            block = {key: arr[:, i].copy() for key, arr in data.items()}
            self.host_pool.put(p.seq_hash, block)
            if self.prefix is not None:
                self.prefix.on_offloaded(p.seq_hash)
            if self.remote is not None:
                try:  # write-through to the cluster-shared G4 tier
                    await self.remote.put(p.seq_hash, block)
                    self.stats.g4_puts += 1
                    if self.prefix is not None:
                        self.prefix.on_g4_put(p.seq_hash)
                except Exception:
                    log.exception("G4 put failed for %x", p.seq_hash)
        if self.peers is not None:
            try:  # one batched presence update, not a put per block
                await self.peers.publish_many(
                    [p.seq_hash for p in batch]
                )
            except Exception:
                log.exception("peer G2 publish failed")
        self.stats.offloaded_blocks += len(batch)
        return len(batch)

    # ------------------------- onboarding ------------------------------

    async def onboard_prefix(self, token_seq) -> int:
        """Promote host-held leading blocks of ``token_seq`` into the G1
        prefix cache (adopt + one batched scatter). Returns blocks
        onboarded. Called by the engine at admission, before scheduling."""
        pool = self.engine.scheduler.pool
        peer_hits_before = self.stats.peer_hits
        candidates = token_seq.blocks[: self.config.max_onboard_blocks]
        peer_data: Dict[int, Dict[str, np.ndarray]] = {}
        if self.peers is not None:
            # one batched peer lookup+fetch for every locally-missing hash
            # (a per-block round-trip would serialise hundreds of RTTs at
            # admission); may over-fetch past the first break point, bounded
            # by max_onboard_blocks
            need = [
                tb.sequence_hash for tb in candidates
                if not pool.contains(tb.sequence_hash)
                and tb.sequence_hash not in self.host_pool
            ]
            if need:
                try:
                    peer_data = await self.peers.fetch_many(need)
                except Exception:
                    log.exception("peer G2 batch fetch failed")
        adopted: List[Tuple[int, Dict[str, np.ndarray]]] = []
        try:
            for tb in candidates:
                if pool.contains(tb.sequence_hash):
                    continue  # native G1 hit — prefix matching will take it
                data = self.host_pool.get(tb.sequence_hash)
                if data is None:
                    data = peer_data.get(tb.sequence_hash)
                    if data is not None:
                        self.stats.peer_hits += 1
                        self.host_pool.put(tb.sequence_hash, data)
                        if self.prefix is not None:
                            self.prefix.on_offloaded(tb.sequence_hash)
                if data is None and self.remote is not None:
                    try:
                        data = await self.remote.get(tb.sequence_hash)
                    except Exception:
                        log.exception("G4 get failed")
                        data = None
                    if data is not None:
                        self.stats.g4_hits += 1
                        self.host_pool.put(tb.sequence_hash, data)  # promote
                        if self.prefix is not None:
                            self.prefix.on_offloaded(tb.sequence_hash)
                if data is None:
                    break  # chained hashes: deeper blocks can't hit either
                bid = pool.adopt(
                    tb.sequence_hash, tb.block_hash, tb.parent_sequence_hash
                )
                if bid is None:
                    break  # G1 full — stop promoting
                adopted.append((bid, data))
            if not adopted:
                return 0
            block_ids = [bid for bid, _ in adopted]
            data = {
                key: np.stack([d[key] for _, d in adopted], axis=1)
                for key in adopted[0][1]
            }
            await self.engine.inject_kv_blocks(block_ids, data)
        except BaseException:
            # injection failed (device error / caller cancelled): the
            # adopted blocks hold no valid KV — discard, never cache them
            for bid, _ in adopted:
                pool.discard_adopted(bid)
            raise
        for bid, _ in adopted:
            pool.release_adopted(bid)
        self.stats.onboarded_blocks += len(adopted)
        if adopted:
            self.stats.onboard_requests += 1
            peer_blocks = self.stats.peer_hits - peer_hits_before
            if peer_blocks:
                log.info("onboarded %d blocks (%d from peer G2)",
                         len(adopted), peer_blocks)
            else:
                log.debug("onboarded %d blocks from host tier",
                          len(adopted))
        return len(adopted)
