"""Multimodal encode-prefill-decode (EPD) support
(ref: components/backends/trtllm — multimodal_processor.py + the EPD
request handlers): a vision ENCODE worker turns media into prompt
embeddings; prefill splices them over placeholder tokens; decode is
unchanged. TPU-native: the encoder is one jitted patchify+transformer
program, embeddings ride the wire as binary arrays, and KV block hashes
are content-addressed over the media so the prefix cache can never serve
one image's KV for another."""

from .encoder import (
    EncodeHandler, VisionEncoder, VisionEncoderConfig,
    array_from_wire, array_to_wire,
)
from .processor import MM_MARKER, MultimodalProcessor

__all__ = [
    "EncodeHandler",
    "VisionEncoder",
    "VisionEncoderConfig",
    "MultimodalProcessor",
    "MM_MARKER",
    "array_to_wire",
    "array_from_wire",
]
