"""Standalone vision ENCODE worker — the E in EPD
(ref: the TRT-LLM encode worker role). Serves the ``encode`` endpoint on
its own component; language workers advertise it via
``--mm-encode-component``.

    python -m dynamo_tpu.multimodal --component encoder --model-dim 2048
"""

from __future__ import annotations

import argparse
import asyncio

from ..runtime.component import DistributedRuntime
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger
from .encoder import EncodeHandler, VisionEncoder, VisionEncoderConfig

log = get_logger("mm.worker")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu encode worker")
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--component", default="encoder")
    p.add_argument("--advertise-host", default="127.0.0.1")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--patch-size", type=int, default=8)
    p.add_argument("--model-dim", type=int, required=True,
                   help="language model hidden size the embeddings target")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(config)

    cfg = VisionEncoderConfig(
        image_size=args.image_size, patch_size=args.patch_size,
        model_dim=args.model_dim,
    )
    handler = EncodeHandler(VisionEncoder(cfg, seed=args.seed))
    ep = (runtime.namespace().component(args.component).endpoint("encode"))
    await ep.serve_endpoint(handler, advertise_host=args.advertise_host)
    log.info(
        "encode worker ready: %dx%d px -> %d tokens x %d dim",
        cfg.image_size, cfg.image_size, cfg.tokens_per_image, cfg.model_dim,
    )
    await runtime.shutdown_event.wait()


def main(argv=None) -> None:
    asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    main()
