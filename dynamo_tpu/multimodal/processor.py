"""Multimodal request processing: image extraction, prompt splicing, and
the encode-first orchestration
(ref: components/backends/trtllm/src/dynamo/trtllm/multimodal_processor.py
— the reference extracts media from OpenAI message content parts, runs the
encode step, and splices prompt embeddings; same contract here).

Flow (EPD):

  chat request with image content parts
    → extract images (data: URLs carrying raw .npy bytes, or inline
      nested-list arrays)
    → messages rendered with each image part replaced by MM_MARKER
    → the rendered prompt is split on MM_MARKER and the text segments
      tokenized independently; each image contributes a run of
      ``tokens_per_image`` placeholder ids between segments
    → the ENCODE worker (or a local encoder) turns images into embedding
      arrays
    → the wire request carries {positions, embeddings}; the engine's
      multimodal prefill splices them over the placeholder rows.

Cache correctness: block hashes are computed over token ids, and every
image uses the same placeholder id — so two prompts differing only in the
image would collide. ``content_token`` folds each image's CONTENT hash
into the ids used for hashing (not the model inputs), making the prefix
cache content-addressed: same image → legitimate reuse, different image →
different blocks.
"""

from __future__ import annotations

import base64
import io
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import xxhash

from ..runtime.engine import Operator
from ..utils.logging import get_logger
from .encoder import VisionEncoder, array_from_wire, array_to_wire

log = get_logger("mm.processor")

# the string an image part contributes to the rendered chat prompt; the
# processor splits on it, so it must survive the chat template verbatim
MM_MARKER = "<|image|>"

# placeholder token id used for model-input rows that will be overwritten
# by vision embeddings (id 0 is the universal pad across our tokenizers)
PLACEHOLDER_ID = 0


def decode_image_part(part: dict) -> np.ndarray:
    """One OpenAI image content part → float array.

    Accepted: ``image_url.url = data:application/x-npy;base64,...`` (raw
    .npy bytes — the zero-dependency path this image supports) or an
    inline ``{"array": [[...]]}`` nested list."""
    if "array" in part:
        return np.asarray(part["array"], np.float32)
    url = (part.get("image_url") or {}).get("url", "")
    if not url.startswith("data:"):
        raise ValueError(
            "image_url must be a data: URL carrying .npy bytes "
            "(zero-egress deployment — no fetching)"
        )
    try:
        payload = base64.b64decode(url.split(",", 1)[1])
        return np.load(io.BytesIO(payload), allow_pickle=False)
    except Exception as exc:
        raise ValueError(f"undecodable image payload: {exc}") from None


def content_token(image: np.ndarray, index: int) -> int:
    """Content-addressed stand-in id for HASHING (never a model input):
    folds the image bytes into the KV block hash chain."""
    h = xxhash.xxh3_64_intdigest(
        np.ascontiguousarray(image, np.float32).tobytes(), seed=index
    )
    # token ids hash as u32; the top bit keeps content ids clear of any
    # real vocab (vocabs are < 2^31), with 31 bits of content entropy
    return int(h & 0x7FFFFFFF) | 0x80000000


class MultimodalProcessor:
    """Splices images into a tokenized prompt and fetches embeddings.

    ``encode_client`` is a component Client for the encode worker's
    endpoint (EPD: encode runs on its own worker); ``local_encoder`` is
    the in-process fallback (aggregated deployments / tests)."""

    def __init__(self, tokenizer, tokens_per_image: int,
                 encode_client=None,
                 local_encoder: Optional[VisionEncoder] = None):
        if encode_client is None and local_encoder is None:
            raise ValueError("need an encode client or a local encoder")
        self.tokenizer = tokenizer
        self.tokens_per_image = tokens_per_image
        self.encode_client = encode_client
        self.local_encoder = local_encoder

    # ------------------------ message handling -------------------------

    @staticmethod
    def has_media(messages: List[dict]) -> bool:
        for m in messages:
            content = m.get("content")
            if isinstance(content, list) and any(
                isinstance(p, dict)
                and p.get("type") in ("image_url", "image")
                for p in content
            ):
                return True
        return False

    @staticmethod
    def extract(messages: List[dict]) -> Tuple[List[dict], List[np.ndarray]]:
        """Replace image parts with MM_MARKER text; collect the arrays in
        prompt order."""
        images: List[np.ndarray] = []
        out: List[dict] = []
        for m in messages:
            content = m.get("content")
            if not isinstance(content, list):
                out.append(m)
                continue
            text_parts: List[str] = []
            for part in content:
                if not isinstance(part, dict):
                    continue
                if part.get("type") in ("image_url", "image"):
                    images.append(decode_image_part(part))
                    text_parts.append(MM_MARKER)
                elif part.get("type") in ("text", "input_text"):
                    text_parts.append(part.get("text", ""))
            out.append({**m, "content": "".join(text_parts)})
        return out, images

    # -------------------------- tokenisation ---------------------------

    def splice(self, rendered: str,
               images: List[np.ndarray]) -> Tuple[List[int], List[int],
                                                  List[int]]:
        """Rendered prompt (with MM_MARKERs) → (token_ids, mm_positions,
        hash_token_ids). Text segments are tokenized independently around
        the markers (the standard split-on-marker assembly)."""
        segments = rendered.split(MM_MARKER)
        if len(segments) - 1 != len(images):
            raise ValueError(
                f"{len(segments) - 1} image markers vs "
                f"{len(images)} images"
            )
        ids: List[int] = []
        hash_ids: List[int] = []
        positions: List[int] = []
        for i, seg in enumerate(segments):
            seg_ids = self.tokenizer.encode(seg) if seg else []
            ids.extend(seg_ids)
            hash_ids.extend(seg_ids)
            if i < len(images):
                start = len(ids)
                run = self.tokens_per_image
                positions.extend(range(start, start + run))
                ids.extend([PLACEHOLDER_ID] * run)
                ct = content_token(images[i], i)
                # content-addressed hash ids: fold position so repeated
                # identical images still chain distinctly; ids must stay
                # u32 (block hashing packs '<I') with the vocab-clear top
                # bit pinned
                hash_ids.extend(
                    0x80000000 | ((ct + j) & 0x7FFFFFFF)
                    for j in range(run)
                )
        return ids, positions, hash_ids

    # --------------------------- encoding ------------------------------

    async def encode(self, images: List[np.ndarray]) -> List[np.ndarray]:
        if self.encode_client is not None:
            from ..runtime.context import Context

            async for out in self.encode_client.round_robin(
                {"images": [array_to_wire(i) for i in images]}, Context()
            ):
                if out.get("tokens_per_image") != self.tokens_per_image:
                    raise ValueError(
                        "encode worker tokens_per_image "
                        f"{out.get('tokens_per_image')} != processor "
                        f"{self.tokens_per_image}"
                    )
                return [array_from_wire(e) for e in out["embeddings"]]
            raise RuntimeError("encode worker returned no response")
        return [self.local_encoder.encode(i) for i in images]

    async def process(self, rendered: str,
                      images: List[np.ndarray]) -> Tuple[List[int], dict]:
        """→ (token_ids, mm wire dict for the engine)."""
        ids, positions, hash_ids = self.splice(rendered, images)
        embeds = await self.encode(images)
        flat = np.concatenate(embeds, axis=0) if embeds else np.zeros(
            (0, 1), np.float32)
        if flat.shape[0] != len(positions):
            raise ValueError(
                f"{flat.shape[0]} embedding rows vs "
                f"{len(positions)} placeholder positions"
            )
        return ids, {
            "positions": positions,
            "embeddings": array_to_wire(flat.astype(np.float32)),
            "hash_token_ids": hash_ids,
        }


class MultimodalPreprocessor(Operator):
    """Preprocessor operator variant handling image content parts: extract
    → encode (EPD) → splice, falling back to the plain text path when no
    media is present. Drop-in for llm.preprocessor.Preprocessor in
    build_routed_pipeline."""

    def __init__(self, inner, processor: MultimodalProcessor):
        self.inner = inner          # llm.preprocessor.Preprocessor
        self.mm = processor

    async def forward(self, request: Any, context) -> Any:
        req = request
        if (not isinstance(req, dict) or "messages" not in req
                or not MultimodalProcessor.has_media(req["messages"])):
            return await self.inner.forward(request, context)

        text_messages, images = MultimodalProcessor.extract(req["messages"])
        rendered = self.inner.template.render(
            messages=text_messages, add_generation_prompt=True
        )
        token_ids, mm = await self.mm.process(rendered, images)
        bos = self.inner.tokenizer.bos_token_id
        if bos is not None and (not token_ids or token_ids[0] != bos):
            token_ids = [bos] + token_ids
            mm["positions"] = [p + 1 for p in mm["positions"]]
            mm["hash_token_ids"] = [bos] + mm["hash_token_ids"]
        # sampling/stop/annotation assembly shared with the text path so
        # the two can never drift
        out = self.inner.build_request(req, token_ids, formatted=rendered)
        out.mm = mm
        return out

    def backward(self, stream, request: Any, context):
        return self.inner.backward(stream, request, context)
