"""Vision encoder: image → prompt embeddings, as ONE jitted TPU program
(role of the reference's encode worker in the TRT-LLM EPD flow — there a
full vision tower inside the engine; here a compact ViT-style patchifier:
conv-as-matmul patch embedding + a few pre-norm attention/MLP blocks +
projection to the language model's hidden size, all MXU-friendly matmuls
with static shapes).

The encode worker serves this behind an ``encode`` endpoint; embeddings
travel as raw binary arrays (`array_to_wire`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..utils.logging import get_logger

log = get_logger("mm.encoder")


# ----------------------------- wire codec ---------------------------------


def array_to_wire(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {"d": a.tobytes(), "t": a.dtype.str, "s": list(a.shape)}


def array_from_wire(m: dict) -> np.ndarray:
    return np.frombuffer(m["d"], np.dtype(m["t"])).reshape(m["s"]).copy()


# ------------------------------ the model ---------------------------------


@dataclass(frozen=True)
class VisionEncoderConfig:
    image_size: int = 32          # square inputs (resized by the processor)
    patch_size: int = 8
    channels: int = 3
    width: int = 64               # encoder hidden size
    num_layers: int = 2
    num_heads: int = 4
    model_dim: int = 64           # language model hidden size (projection)

    def __post_init__(self):
        if self.image_size % self.patch_size != 0:
            raise ValueError(
                f"image_size {self.image_size} not divisible by "
                f"patch_size {self.patch_size}"
            )
        if self.width % self.num_heads != 0:
            raise ValueError(
                f"width {self.width} not divisible by num_heads "
                f"{self.num_heads}"
            )

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def tokens_per_image(self) -> int:
        return self.num_patches

    @staticmethod
    def tiny(model_dim: int = 64) -> "VisionEncoderConfig":
        return VisionEncoderConfig(model_dim=model_dim)


def init_vision_params(rng: jax.Array, cfg: VisionEncoderConfig) -> Dict:
    p = cfg.patch_size
    in_dim = p * p * cfg.channels
    W, F = cfg.width, cfg.width * 4
    keys = jax.random.split(rng, 10)

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / np.sqrt(fan_in)).astype(jnp.float32)

    L = cfg.num_layers
    return {
        "patch": norm(keys[0], (in_dim, W), in_dim),
        "pos": norm(keys[1], (cfg.num_patches, W), W),
        "layers": {
            "ln1": jnp.ones((L, W), jnp.float32),
            "wqkv": norm(keys[2], (L, W, 3 * W), W),
            "wo": norm(keys[3], (L, W, W), W),
            "ln2": jnp.ones((L, W), jnp.float32),
            "w1": norm(keys[4], (L, W, F), W),
            "w2": norm(keys[5], (L, F, W), F),
        },
        "ln_f": jnp.ones((W,), jnp.float32),
        "proj": norm(keys[6], (W, cfg.model_dim), W),
    }


def encode_image(cfg: VisionEncoderConfig, params: Dict,
                 image: jax.Array) -> jax.Array:
    """[H, W, C] float32 in [0, 1] → [num_patches, model_dim]."""
    p = cfg.patch_size
    n = cfg.image_size // p
    H = cfg.num_heads
    hd = cfg.width // H
    # patchify: conv-as-matmul ([N, p*p*C] @ [p*p*C, W] rides the MXU)
    x = image.reshape(n, p, n, p, cfg.channels)
    x = x.transpose(0, 2, 1, 3, 4).reshape(n * n, p * p * cfg.channels)
    h = x @ params["patch"] + params["pos"]              # [N, W]

    def ln(x, w):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * w

    stacked = params["layers"]
    for li in range(cfg.num_layers):
        lp = {k: v[li] for k, v in stacked.items()}
        x = ln(h, lp["ln1"])
        qkv = (x @ lp["wqkv"]).reshape(-1, 3, H, hd)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]        # [N, H, hd]
        s = jnp.einsum("qhd,khd->hqk", q, k) / np.sqrt(hd)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hqk,khd->qhd", a, v).reshape(-1, cfg.width)
        h = h + o @ lp["wo"]
        x = ln(h, lp["ln2"])
        h = h + jax.nn.gelu(x @ lp["w1"]) @ lp["w2"]
    h = ln(h, params["ln_f"])
    return h @ params["proj"]                            # [N, model_dim]


class VisionEncoder:
    """Jit-compiled encoder with deterministic params from a seed (the
    language worker derives the same placeholder count from the config)."""

    def __init__(self, config: VisionEncoderConfig, seed: int = 0):
        self.config = config
        self.params = init_vision_params(jax.random.PRNGKey(seed), config)
        self._fn = jax.jit(lambda img: encode_image(config, self.params, img))
        self.num_encoded = 0

    def encode(self, image: np.ndarray) -> np.ndarray:
        """[H, W, C] (any float/int dtype; resized/cropped by caller) →
        [tokens_per_image, model_dim] float32."""
        cfg = self.config
        # dtype decides normalisation — a value heuristic would leave a
        # near-black uint8 image unscaled and encode it inconsistently
        is_int = np.issubdtype(np.asarray(image).dtype, np.integer)
        img = np.asarray(image, np.float32)
        if is_int:
            img = img / 255.0
        if img.ndim == 2:
            img = np.repeat(img[:, :, None], cfg.channels, axis=2)
        if img.shape != (cfg.image_size, cfg.image_size, cfg.channels):
            img = _resize_nearest(
                img, cfg.image_size, cfg.image_size, cfg.channels
            )
        out = np.asarray(jax.device_get(self._fn(jnp.asarray(img))))
        self.num_encoded += 1
        return out


def _resize_nearest(img: np.ndarray, h: int, w: int, c: int) -> np.ndarray:
    ys = (np.arange(h) * img.shape[0] / h).astype(int)
    xs = (np.arange(w) * img.shape[1] / w).astype(int)
    out = img[ys][:, xs]
    if out.shape[2] > c:
        out = out[:, :, :c]
    elif out.shape[2] < c:
        out = np.repeat(out[:, :, :1], c, axis=2)
    return out


class EncodeHandler(AsyncEngine):
    """The encode worker's wire endpoint: images in, embeddings out
    (served as ``encode`` next to the language worker's ``generate``).

    Encoding is blocking jitted device work — it runs on a dedicated
    executor thread so a colocated language worker's event loop keeps
    pumping token streams while images encode."""

    def __init__(self, encoder: VisionEncoder):
        import concurrent.futures

        self.encoder = encoder
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="mm-encode"
        )

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        import asyncio

        loop = asyncio.get_running_loop()
        embeddings = []
        for img_wire in request.get("images", []):
            out = await loop.run_in_executor(
                self._executor, self.encoder.encode,
                array_from_wire(img_wire),
            )
            embeddings.append(array_to_wire(out))
        yield {
            "embeddings": embeddings,
            "tokens_per_image": self.encoder.config.tokens_per_image,
        }
