"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

Capability-equivalent to NVIDIA Dynamo (see SURVEY.md) but designed TPU-first:
the model engine is JAX/XLA (pjit-sharded transformers, paged HBM KV cache,
Pallas kernels), intra-model parallelism rides ICI via jax.sharding, and the
KV-block data plane uses XLA collectives / device-to-device transfers instead
of NIXL RDMA. The host-side control plane (discovery, leases, request
transport, response streams) follows the reference's protocol shapes
(ref: lib/runtime/src/lib.rs, lib/llm/src/lib.rs).
"""

__version__ = "0.1.0"
