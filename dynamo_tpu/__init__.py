"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

Capability-equivalent to NVIDIA Dynamo (see SURVEY.md) but designed TPU-first:
the model engine is JAX/XLA (pjit-sharded transformers, paged HBM KV cache,
Pallas kernels), intra-model parallelism rides ICI via jax.sharding, and the
KV-block data plane uses XLA collectives / device-to-device transfers instead
of NIXL RDMA. The host-side control plane (discovery, leases, request
transport, response streams) follows the reference's protocol shapes
(ref: lib/runtime/src/lib.rs, lib/llm/src/lib.rs).
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS") == "cpu":
    # Honor an explicit CPU request deterministically. Site customizations
    # that register accelerator plugins at interpreter startup can override
    # the env var with an "accelerator,cpu" preference list; if the
    # accelerator's backend init then hangs (e.g. an unreachable TPU
    # tunnel), every CPU-intended child process hangs with it. The config
    # update wins over the startup-time preference (same trick as
    # tests/conftest.py).
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
