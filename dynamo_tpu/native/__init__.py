"""ctypes loader for the native hot-loop library (native/src/*.cpp).

Role-equivalent to the reference's native crates for token hashing and the
router radix index (ref: lib/tokens/src/lib.rs, kv_router/indexer.rs:224).
Builds the .so with g++ on first use if missing; every entry point has a
pure-Python fallback, so the framework runs (slower) without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger

log = get_logger("native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdynamo_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    try:
        import pyarrow

        src = os.path.join(_NATIVE_DIR, "src", "dynamo_native.cpp")
        cmd = [
            os.environ.get("CXX", "g++"), "-O3", "-fPIC", "-shared",
            "-std=c++17", "-Wall", f"-I{pyarrow.get_include()}",
            "-o", _SO_PATH, src,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:
        log.warning("native build failed (%s) — using Python fallbacks", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            log.warning("native load failed (%s)", e)
            _build_failed = True
            return None
        lib.dyn_block_hashes.restype = ctypes.c_int64
        lib.dyn_block_hashes.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.dyn_index_new.restype = ctypes.c_void_p
        lib.dyn_index_free.argtypes = [ctypes.c_void_p]
        for name in ("dyn_index_stored", "dyn_index_removed"):
            fn = getattr(lib, name)
            fn.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                           ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        lib.dyn_index_clear_worker.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64]
        lib.dyn_index_num_blocks.restype = ctypes.c_int64
        lib.dyn_index_num_blocks.argtypes = [ctypes.c_void_p]
        lib.dyn_index_find_matches.restype = ctypes.c_int64
        lib.dyn_index_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        _lib = lib
        log.info("native library loaded: %s", _SO_PATH)
    return _lib


def available() -> bool:
    return get_lib() is not None


# ------------------------------ hashing -----------------------------------


def block_hashes(
    tokens, block_size: int, seed: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(block_hashes, sequence_hashes) for complete blocks via the native
    path, or None when the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    toks = np.ascontiguousarray(tokens, dtype=np.uint32)
    n_blocks = len(toks) // block_size
    bh = np.empty(n_blocks, np.uint64)
    sh = np.empty(n_blocks, np.uint64)
    got = lib.dyn_block_hashes(
        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(toks),
        block_size, seed,
        bh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        sh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    assert got == n_blocks
    return bh, sh


# ---------------------------- prefix index ---------------------------------


class NativePrefixIndex:
    """C++ longest-prefix matcher (chained sequence hashes → workers)."""

    def __init__(self):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.dyn_index_new()

    def close(self) -> None:
        if self._h is not None:
            self._lib.dyn_index_free(self._h)
            self._h = None

    __del__ = close

    @staticmethod
    def _arr(hashes) -> np.ndarray:
        return np.ascontiguousarray(hashes, dtype=np.uint64)

    def stored(self, worker: int, seq_hashes) -> None:
        a = self._arr(seq_hashes)
        self._lib.dyn_index_stored(
            self._h, worker,
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(a))

    def removed(self, worker: int, seq_hashes) -> None:
        a = self._arr(seq_hashes)
        self._lib.dyn_index_removed(
            self._h, worker,
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(a))

    def clear_worker(self, worker: int) -> None:
        self._lib.dyn_index_clear_worker(self._h, worker)

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_index_num_blocks(self._h)

    def find_matches(self, seq_hashes, max_workers: int = 4096
                     ) -> dict:
        a = self._arr(seq_hashes)
        workers = np.empty(max_workers, np.uint64)
        depths = np.empty(max_workers, np.int64)
        n = self._lib.dyn_index_find_matches(
            self._h, a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(a),
            workers.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            depths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_workers,
        )
        return {int(workers[i]): int(depths[i]) for i in range(n)}
