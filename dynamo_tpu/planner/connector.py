"""Scaling connectors (ref: components/planner/src/dynamo/planner/
virtual_connector.py:316, kubernetes_connector.py).

``VirtualConnector`` records target replica counts in the store — an
orchestrator (test harness, launch script, or the k8s operator equivalent)
watches ``planner/{namespace}/target`` and realises them. This is the same
decoupling the reference uses to test the planner without a cluster.

Decision IDs are persisted in the store (``planner/{ns}/state``) so they
stay monotonic across planner restarts, and a ``scale()`` to an unchanged
target is a no-op — the orchestrator never sees a redundant revision for
intent it already realised.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import msgpack

# planner transitions (scaling decisions, degradation ladder moves) are
# broadcast here so the metrics aggregator can expose them as gauges
PLANNER_EVENTS_SUBJECT = "planner_events"


def planner_events_subject(namespace: str) -> str:
    return f"v1/events/{namespace}/planner/{PLANNER_EVENTS_SUBJECT}/"


class VirtualConnector:
    """Store-backed scaling intent; no processes are touched."""

    def __init__(self, store, namespace: str = "dynamo"):
        self.store = store
        self.namespace = namespace
        self.decision_count = 0
        self._loaded = False
        self._last: Dict[str, int] = {}
        self._last_degradation: Optional[dict] = None

    def _key(self, component: str) -> str:
        return f"planner/{self.namespace}/target/{component}"

    @property
    def _state_key(self) -> str:
        return f"planner/{self.namespace}/state"

    @property
    def _degradation_key(self) -> str:
        return f"planner/{self.namespace}/degradation"

    async def _ensure_loaded(self) -> None:
        """Restore decision_count + last targets from a previous planner
        incarnation so IDs stay monotonic and unchanged targets are not
        re-put after a restart."""
        if self._loaded:
            return
        raw = await self.store.get(self._state_key)
        if raw is not None:
            state = json.loads(raw)
            self.decision_count = max(
                self.decision_count, int(state.get("decision_count", 0))
            )
        targets = await self.store.get_prefix(
            f"planner/{self.namespace}/target/"
        )
        for key, value in targets:
            component = key.rsplit("/", 1)[-1]
            try:
                self._last[component] = int(json.loads(value)["replicas"])
            except Exception:
                pass
        self._loaded = True

    async def scale(self, component: str, replicas: int) -> None:
        await self._ensure_loaded()
        replicas = int(replicas)
        if self._last.get(component) == replicas:
            return  # intent already recorded — don't burn a decision ID
        self.decision_count += 1
        await self.store.put(self._key(component), json.dumps({
            "replicas": replicas,
            "ts": time.time(),
            "decision": self.decision_count,
        }).encode())
        await self.store.put(self._state_key, json.dumps({
            "decision_count": self.decision_count,
        }).encode())
        self._last[component] = replicas

    async def read_target(self, component: str) -> Optional[int]:
        raw = await self.store.get(self._key(component))
        if raw is None:
            return None
        return int(json.loads(raw)["replicas"])

    # -------------------- degradation ladder intent ---------------------

    async def set_degradation(self, actions: dict) -> None:
        """Publish the ladder's current orders (level + knob clamps) for
        frontends/workers to apply; unchanged orders are not re-put."""
        if actions == self._last_degradation:
            return
        payload = dict(actions)
        payload["ts"] = time.time()
        await self.store.put(
            self._degradation_key, json.dumps(payload).encode()
        )
        self._last_degradation = dict(actions)

    async def read_degradation(self) -> Optional[dict]:
        raw = await self.store.get(self._degradation_key)
        return None if raw is None else json.loads(raw)

    # ------------------------- event broadcast --------------------------

    async def publish_event(self, event: dict) -> None:
        """Best-effort broadcast of a planner transition (scale decision or
        ladder move) for the aggregator's gauges."""
        try:
            await self.store.publish(
                planner_events_subject(self.namespace),
                msgpack.packb(event, use_bin_type=True),
            )
        except Exception:
            pass  # observability must never block control


class CallbackConnector:
    """In-process connector for unit tests: records scale() calls."""

    def __init__(self):
        self.calls: list = []
        self.targets: Dict[str, int] = {}
        self.degradations: list = []
        self.events: list = []

    async def scale(self, component: str, replicas: int) -> None:
        self.calls.append((component, int(replicas)))
        self.targets[component] = int(replicas)

    async def set_degradation(self, actions: dict) -> None:
        self.degradations.append(dict(actions))

    async def publish_event(self, event: dict) -> None:
        self.events.append(dict(event))
