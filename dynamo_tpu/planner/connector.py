"""Scaling connectors (ref: components/planner/src/dynamo/planner/
virtual_connector.py:316, kubernetes_connector.py).

``VirtualConnector`` records target replica counts in the store — an
orchestrator (test harness, launch script, or the k8s operator equivalent)
watches ``planner/{namespace}/target`` and realises them. This is the same
decoupling the reference uses to test the planner without a cluster.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional


class VirtualConnector:
    """Store-backed scaling intent; no processes are touched."""

    def __init__(self, store, namespace: str = "dynamo"):
        self.store = store
        self.namespace = namespace
        self.decision_count = 0

    def _key(self, component: str) -> str:
        return f"planner/{self.namespace}/target/{component}"

    async def scale(self, component: str, replicas: int) -> None:
        self.decision_count += 1
        await self.store.put(self._key(component), json.dumps({
            "replicas": int(replicas),
            "ts": time.time(),
            "decision": self.decision_count,
        }).encode())

    async def read_target(self, component: str) -> Optional[int]:
        raw = await self.store.get(self._key(component))
        if raw is None:
            return None
        return int(json.loads(raw)["replicas"])


class CallbackConnector:
    """In-process connector for unit tests: records scale() calls."""

    def __init__(self):
        self.calls: list = []
        self.targets: Dict[str, int] = {}

    async def scale(self, component: str, replicas: int) -> None:
        self.calls.append((component, int(replicas)))
        self.targets[component] = int(replicas)
