"""SLA-based autoscaling planner (ref: components/planner — planner_core.py,
perf_interpolation.py, load_predictor.py, virtual_connector.py).

Observes frontend/worker metrics (tail percentiles, queue depth, breaker
states, spec acceptance), orders graceful degradation under pressure,
predicts the next window's load, converts it into prefill/decode replica
counts via pre-profiled perf interpolation, and emits scaling decisions
through a connector (store-backed virtual connector here; a k8s connector
is the deploy-layer analog). The :class:`Orchestrator` realises the intent
against a live worker pool — role flips first, spawns/stops for the rest.
"""

from .connector import CallbackConnector, VirtualConnector
from .core import Planner, PlannerConfig, WindowMetrics
from .degradation import (
    DegradationConfig, DegradationLadder, DegradationWatcher, STEPS,
)
from .interpolation import DecodeInterpolator, PrefillInterpolator
from .orchestrator import Orchestrator, WorkerPool
from .predictors import ARPredictor, ConstantPredictor, MovingAveragePredictor

__all__ = [
    "Planner", "PlannerConfig", "WindowMetrics",
    "PrefillInterpolator", "DecodeInterpolator",
    "ConstantPredictor", "MovingAveragePredictor", "ARPredictor",
    "VirtualConnector", "CallbackConnector",
    "DegradationConfig", "DegradationLadder", "DegradationWatcher", "STEPS",
    "Orchestrator", "WorkerPool",
]
