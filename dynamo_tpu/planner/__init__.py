"""SLA-based autoscaling planner (ref: components/planner — planner_core.py,
perf_interpolation.py, load_predictor.py, virtual_connector.py).

Observes frontend/worker metrics, predicts the next window's load, converts
it into prefill/decode replica counts via pre-profiled perf interpolation,
and emits scaling decisions through a connector (store-backed virtual
connector here; a k8s connector is the deploy-layer analog).
"""

from .connector import VirtualConnector
from .core import Planner, PlannerConfig, WindowMetrics
from .interpolation import DecodeInterpolator, PrefillInterpolator
from .predictors import ARPredictor, ConstantPredictor, MovingAveragePredictor

__all__ = [
    "Planner", "PlannerConfig", "WindowMetrics",
    "PrefillInterpolator", "DecodeInterpolator",
    "ConstantPredictor", "MovingAveragePredictor", "ARPredictor",
    "VirtualConnector",
]
