"""Graceful-degradation ladder: cheap relief the planner orders BEFORE
spending chips (ref: the overload-ladder pattern of *Taming the Chaos* —
shed, then cheapen, then scale).

The ladder is an ordered list of reversible steps:

    1. ``evict_to_host``     — demote LRU subtrees of sealed, idle prefix
                               blocks from G1 HBM to the KVBM host pool
                               (prefix.manager ``evict_to_host``): frees
                               device pages for running work while keeping
                               the prefixes onboardable, so it engages
                               BEFORE any request is turned away
    2. ``shed_low_tier``     — admission sheds requests below ``shed_tier``
                               (PR-1 admission controller, tier-aware)
    3. ``clamp_spec_k``      — cap speculative draft length (verify windows
                               stop amplifying decode latency under load)
    4. ``tighten_chunking``  — cap ``prefill_chunk_tokens`` so long prompts
                               stop stalling running decodes

Pressure is the worst SLO overshoot ratio observed in the last window
(``max(ttft_p99/ttft_sla, itl_p99/itl_sla)``). Each window the ladder moves
at most ONE step: engage the next step while pressure ≥ ``engage_ratio``,
release the most recent step once pressure ≤ ``release_ratio`` — strictly
reverse order, with hysteresis between the two thresholds so the ladder
never flaps. Every transition is emitted as a trace span (name
``planner.degradation``), and the aggregator mirrors the level as the
``planner_degradation_level`` gauge via the planner-events subject.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from .. import tracing
from ..utils.logging import get_logger

log = get_logger("planner.degradation")

# engagement order; released strictly in reverse
STEPS: Tuple[str, ...] = (
    "evict_to_host", "shed_low_tier", "clamp_spec_k", "tighten_chunking",
)


@dataclass
class DegradationConfig:
    engage_ratio: float = 1.5     # pressure at/above which the next step engages
    release_ratio: float = 1.0    # pressure at/below which the last step releases
    shed_tier: int = 1            # min admitted tier while shed_low_tier holds
    spec_k_clamp: int = 1         # spec_k ceiling while clamp_spec_k holds
    chunk_clamp_tokens: int = 256  # prefill_chunk_tokens ceiling while held
    # G1 blocks each worker demotes to its host pool per window while the
    # evict_to_host rung holds
    evict_to_host_blocks: int = 64


class DegradationLadder:
    """Ordered engage/release state machine over :data:`STEPS`."""

    def __init__(self, config: Optional[DegradationConfig] = None):
        self.config = config or DegradationConfig()
        self.level = 0  # number of engaged steps, 0..len(STEPS)
        self.transitions: List[Tuple[str, str]] = []  # (direction, step)

    @property
    def engaged(self) -> Tuple[str, ...]:
        return STEPS[: self.level]

    def update(self, pressure: float) -> Optional[Tuple[str, str]]:
        """Advance at most one step for this window's pressure; returns the
        transition ``(direction, step)`` or None."""
        cfg = self.config
        if pressure >= cfg.engage_ratio and self.level < len(STEPS):
            step = STEPS[self.level]
            self.level += 1
            return self._record("engage", step, pressure)
        if pressure <= cfg.release_ratio and self.level > 0:
            self.level -= 1
            step = STEPS[self.level]
            return self._record("release", step, pressure)
        return None

    def _record(self, direction: str, step: str,
                pressure: float) -> Tuple[str, str]:
        self.transitions.append((direction, step))
        log.info("degradation %s %s (level=%d pressure=%.2f)",
                 direction, step, self.level, pressure)
        span = tracing.get_tracer().start_span(
            "planner.degradation", root=True,
            attrs={"step": step, "direction": direction,
                   "level": self.level, "pressure": round(pressure, 3)},
        )
        span.end()
        return direction, step

    def actions(self) -> dict:
        """Current knob orders for frontends/workers (the store payload)."""
        cfg = self.config
        engaged = self.engaged
        return {
            "level": self.level,
            "steps": list(engaged),
            "evict_to_host": (cfg.evict_to_host_blocks
                              if "evict_to_host" in engaged else 0),
            "min_tier": cfg.shed_tier if "shed_low_tier" in engaged else 0,
            "spec_k_max": (cfg.spec_k_clamp
                           if "clamp_spec_k" in engaged else None),
            "prefill_chunk_tokens_max": (
                cfg.chunk_clamp_tokens
                if "tighten_chunking" in engaged else None),
        }


NO_DEGRADATION = {
    "level": 0, "steps": [], "evict_to_host": 0, "min_tier": 0,
    "spec_k_max": None, "prefill_chunk_tokens_max": None,
}


def apply_engine_clamps(eng_cfg, actions: dict, originals: dict) -> dict:
    """Clamp a live EngineConfig per the ladder's orders, restoring the
    original values when a step releases. ``originals`` persists the
    pre-clamp values across calls (pass the same dict every time); returns
    the fields changed this call."""
    changed = {}
    for field, key in (("spec_k", "spec_k_max"),
                       ("prefill_chunk_tokens", "prefill_chunk_tokens_max")):
        if not hasattr(eng_cfg, field):
            continue
        cap = actions.get(key)
        current = getattr(eng_cfg, field)
        if cap is not None:
            originals.setdefault(field, current)
            # chunking: 0 means "whole-bucket prefill" — tightening must
            # impose the cap, not min(0, cap)
            if field == "prefill_chunk_tokens" and current == 0:
                clamped = int(cap)
            else:
                clamped = min(int(current), int(cap))
            if clamped != current:
                setattr(eng_cfg, field, clamped)
                changed[field] = clamped
        elif field in originals:
            orig = originals.pop(field)
            if orig != current:
                setattr(eng_cfg, field, orig)
                changed[field] = orig
    return changed


class DegradationWatcher:
    """Polls ``planner/{ns}/degradation`` and invokes ``on_change(actions)``
    whenever the ladder's orders move. Poll-based (like scale_watcher) so a
    store flap degrades to staleness, never to a crash."""

    def __init__(self, store, namespace: str,
                 on_change: Callable[[dict], None],
                 poll_s: float = 1.0):
        self.store = store
        self.namespace = namespace
        self.on_change = on_change
        self.poll_s = poll_s
        self._task: Optional[asyncio.Task] = None
        self._last: Optional[dict] = None

    @property
    def key(self) -> str:
        return f"planner/{self.namespace}/degradation"

    async def poll_once(self) -> Optional[dict]:
        raw = await self.store.get(self.key)
        actions = dict(NO_DEGRADATION) if raw is None else json.loads(raw)
        comparable = {k: v for k, v in actions.items() if k != "ts"}
        if comparable != self._last:
            self._last = comparable
            try:
                self.on_change(comparable)
            except Exception:
                log.exception("degradation on_change failed")
        return comparable

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception as exc:
                log.warning("degradation poll failed: %s", exc)
            await asyncio.sleep(self.poll_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
