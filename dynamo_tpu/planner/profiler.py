"""SLA profiler: sweep the engine offline, emit the planner's perf curves
(ref: benchmarks/profiler/profile_sla.py:56 — sweeps prefill/decode
operating points into the interpolation tables planner_core consumes).

    python -m dynamo_tpu.planner.profiler --model tiny --out profile.json

Prefill curve: for each ISL, time a full-prompt prefill → TTFT and
tok/s/chip. Decode surface: for each (batch, context) point, time steady
decode steps → ITL and tok/s/chip, with kv_usage taken from the pool.
Output keys match ``PrefillInterpolator.from_profile`` /
``DecodeInterpolator.from_profile``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from typing import Dict, List

from ..engine.config import EngineConfig, ModelConfig
from ..engine.engine import InferenceEngine, Request
from ..utils.logging import get_logger

log = get_logger("profiler")

MODEL_PRESETS = {
    "tiny": ModelConfig.tiny,
    "1b": ModelConfig.llama3_1b,
    "8b": ModelConfig.llama3_8b,
    "70b": ModelConfig.llama3_70b,
}


async def _drain(engine: InferenceEngine, req: Request) -> List[float]:
    """Submit one request; returns per-token arrival times."""
    times = []
    async for _ in engine.submit(req):
        times.append(time.perf_counter())
    return times


async def profile_prefill(
    engine: InferenceEngine, isls: List[int], num_chips: int,
) -> Dict[str, list]:
    out = {"prefill_isl": [], "prefill_ttft_s": [],
           "prefill_thpt_per_chip": []}
    for isl in isls:
        prompt = [(i % 1000) + 1 for i in range(isl)]
        # warm-up compiles the bucket
        await _drain(engine, Request(request_id=f"warm-{isl}",
                                     token_ids=prompt, max_tokens=1,
                                     ignore_eos=True))
        t0 = time.perf_counter()
        times = await _drain(engine, Request(
            request_id=f"p-{isl}", token_ids=list(prompt), max_tokens=1,
            ignore_eos=True,
        ))
        ttft = times[0] - t0
        out["prefill_isl"].append(isl)
        out["prefill_ttft_s"].append(ttft)
        out["prefill_thpt_per_chip"].append(isl / ttft / num_chips)
        engine.clear_kv_blocks()
        log.info("prefill isl=%d ttft=%.3fs", isl, ttft)
    return out


async def profile_decode(
    engine: InferenceEngine, points: List[tuple], num_chips: int,
    osl: int = 32,
) -> Dict[str, list]:
    out = {"decode_kv_usage": [], "decode_context_length": [],
           "decode_itl_s": [], "decode_thpt_per_chip": []}
    for batch, context in points:
        reqs = [
            Request(request_id=f"d-{batch}-{context}-{i}",
                    token_ids=[(j % 1000) + 1 for j in range(context)],
                    max_tokens=osl, ignore_eos=True)
            for i in range(batch)
        ]
        peak_usage = 0.0

        async def _sample_usage():
            nonlocal peak_usage
            while True:
                peak_usage = max(peak_usage, engine.scheduler.pool.usage)
                await asyncio.sleep(0.005)

        sampler = asyncio.create_task(_sample_usage())
        t0 = time.perf_counter()
        all_times = await asyncio.gather(
            *(_drain(engine, r) for r in reqs)
        )
        dur = time.perf_counter() - t0
        sampler.cancel()
        itls = [b - a for times in all_times
                for a, b in zip(times, times[1:])]
        itls.sort()
        itl = itls[len(itls) // 2] if itls else 0.0
        total_out = sum(len(t) for t in all_times)
        kv_usage = peak_usage
        out["decode_kv_usage"].append(round(kv_usage, 4))
        out["decode_context_length"].append(context + osl // 2)
        out["decode_itl_s"].append(itl)
        out["decode_thpt_per_chip"].append(total_out / dur / num_chips)
        engine.clear_kv_blocks()
        log.info("decode batch=%d ctx=%d itl=%.4fs kv=%.2f",
                 batch, context, itl, kv_usage)
    return out


async def run_profile(args) -> dict:
    model_cfg = MODEL_PRESETS[args.model]()
    dp, tp = (int(x) for x in args.mesh.split(","))
    num_chips = dp * tp
    isls = [int(x) for x in args.isls.split(",")]
    max_isl = max(isls)
    eng_cfg = EngineConfig(
        num_blocks=args.num_blocks,
        max_model_len=min(2 * max_isl, model_cfg.max_position),
        max_num_batched_tokens=max(512, max_isl),
        prefill_buckets=tuple(sorted({256, max(512, max_isl)})),
        decode_buckets=(8, 16, 32, 64),
        mesh_shape=(dp, tp),
    )
    engine = InferenceEngine(model_cfg, eng_cfg)
    await engine.start()
    try:
        profile = {}
        profile.update(await profile_prefill(engine, isls, num_chips))
        points = []
        for batch in (int(x) for x in args.batches.split(",")):
            for ctx in (int(x) for x in args.contexts.split(",")):
                points.append((batch, ctx))
        profile.update(await profile_decode(engine, points, num_chips))
        profile["meta"] = {
            "model": args.model, "mesh": [dp, tp],
            "num_blocks": args.num_blocks,
        }
        return profile
    finally:
        await engine.stop()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="dynamo-tpu SLA profiler")
    p.add_argument("--model", default="tiny", choices=sorted(MODEL_PRESETS))
    p.add_argument("--mesh", default="1,1")
    p.add_argument("--num-blocks", type=int, default=2048)
    p.add_argument("--isls", default="128,512,1024",
                   help="prefill ISLs to profile (comma-separated)")
    p.add_argument("--batches", default="1,8,32")
    p.add_argument("--contexts", default="128,512")
    p.add_argument("--out", default="profile.json")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    profile = asyncio.run(run_profile(args))
    with open(args.out, "w") as f:
        json.dump(profile, f, indent=2)
    log.info("wrote %s", args.out)


if __name__ == "__main__":
    main()
