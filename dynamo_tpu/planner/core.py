"""Planner core: metrics window → load prediction → replica targets
(ref: components/planner/src/dynamo/planner/utils/planner_core.py —
observe_metrics:193, predict_load:240, _compute_replica_requirements:259).

Every adjustment interval the planner:
1. observes the window's request rate, mean ISL/OSL, and measured TTFT/ITL
   (tail-aware: p99 when the frontend publishes percentiles, average as the
   forward-compat fallback) plus the live pressure signals the serving path
   exposes — admission/worker queue depth, router breaker states, spec
   acceptance;
2. updates correction factors = measured latency / interpolated latency
   (queueing and interference the offline profile can't see);
3. orders graceful degradation (shed → clamp spec_k → tighten chunking)
   while the SLO overshoot is severe, releasing in reverse as it falls;
4. predicts next-window load with per-signal predictors;
5. converts predicted load into prefill/decode replica counts using the
   profiled perf curves, clamps to the chip budget, and emits the targets
   through the connector.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from ..utils.logging import get_logger
from .degradation import DegradationConfig, DegradationLadder
from .interpolation import DecodeInterpolator, PrefillInterpolator
from .predictors import ARPredictor

log = get_logger("planner")


@dataclass
class WindowMetrics:
    """One adjustment window's observed aggregates.

    The percentile fields are optional: pre-PR-7 frontends publish only the
    averages and the planner keeps working on those. ``queue_depth`` is the
    sum of requests waiting anywhere (admission queue + worker queues),
    ``breaker_open`` counts router breakers not currently closed, and
    ``spec_acceptance`` is the aggregate draft acceptance rate (None when
    speculative decoding is off)."""

    num_requests: float
    isl_avg: float
    osl_avg: float
    ttft_avg_s: Optional[float] = None
    itl_avg_s: Optional[float] = None
    ttft_p50_s: Optional[float] = None
    ttft_p99_s: Optional[float] = None
    itl_p50_s: Optional[float] = None
    itl_p99_s: Optional[float] = None
    queue_depth: float = 0.0
    breaker_open: int = 0
    spec_acceptance: Optional[float] = None
    # workers that received a maintenance notice this window: capacity that
    # is evacuating and about to vanish (runtime.preemption)
    preempt_notices: int = 0

    @property
    def is_valid(self) -> bool:
        vals = [self.num_requests, self.isl_avg, self.osl_avg]
        return all(v is not None and v == v and v > 0 for v in vals)

    def ttft_signal(self, quantile: str = "p99") -> Optional[float]:
        """The TTFT the SLA is judged against: the requested percentile when
        published, else the average (old frontends)."""
        if quantile == "p99" and self.ttft_p99_s is not None:
            return self.ttft_p99_s
        if quantile == "p50" and self.ttft_p50_s is not None:
            return self.ttft_p50_s
        return self.ttft_avg_s

    def itl_signal(self, quantile: str = "p99") -> Optional[float]:
        if quantile == "p99" and self.itl_p99_s is not None:
            return self.itl_p99_s
        if quantile == "p50" and self.itl_p50_s is not None:
            return self.itl_p50_s
        return self.itl_avg_s


@dataclass
class PlannerConfig:
    ttft_sla_s: float = 0.5
    itl_sla_s: float = 0.05
    adjustment_interval_s: float = 60.0
    prefill_engine_num_chips: int = 1
    decode_engine_num_chips: int = 1
    min_endpoint: int = 1
    max_chip_budget: int = 64
    predictor_order: int = 4
    # which latency statistic the SLA is enforced on ("p99" | "p50" | "avg")
    sla_quantile: str = "p99"
    # fold the queue backlog into predicted load (a standing queue is demand
    # the arrival rate alone does not show)
    queue_depth_weight: float = 1.0
    # add one decode replica per open breaker: a tripped worker serves
    # nothing, so intent must cover the hole until it heals
    compensate_breakers: bool = True
    # add one decode replica per maintenance-noticed worker: its seats are
    # evacuating and the node is leaving — scale the replacement proactively
    # instead of waiting for the capacity hole to show up in latency
    compensate_preemptions: bool = True
    # graceful degradation before scaling; None disables the ladder
    degradation: Optional[DegradationConfig] = field(
        default_factory=DegradationConfig
    )


class Planner:
    def __init__(
        self,
        config: PlannerConfig,
        prefill: PrefillInterpolator,
        decode: DecodeInterpolator,
        connector,
        prefill_component: str = "prefill",
        decode_component: str = "backend",
    ):
        self.config = config
        self.prefill = prefill
        self.decode = decode
        self.connector = connector
        self.prefill_component = prefill_component
        self.decode_component = decode_component
        p = config.predictor_order
        self._pred_req = ARPredictor(p)
        self._pred_isl = ARPredictor(p)
        self._pred_osl = ARPredictor(p)
        self.p_correction = 1.0
        self.d_correction = 1.0
        self.last_targets = (config.min_endpoint, config.min_endpoint)
        self.last_window: Optional[WindowMetrics] = None
        self.ladder = (DegradationLadder(config.degradation)
                       if config.degradation is not None else None)

    # ------------------------- observation -----------------------------

    def observe(self, m: WindowMetrics) -> None:
        if not m.is_valid:
            return
        self.last_window = m
        self._pred_req.observe(m.num_requests)
        self._pred_isl.observe(m.isl_avg)
        self._pred_osl.observe(m.osl_avg)
        q = self.config.sla_quantile
        ttft = m.ttft_signal(q)
        if ttft:
            expect = self.prefill.interpolate_ttft(m.isl_avg)
            if expect > 0:
                self.p_correction = ttft / expect
        itl = m.itl_signal(q)
        if itl:
            expect = self.decode.interpolate_itl(
                0.5, m.isl_avg + m.osl_avg / 2
            )
            if expect > 0:
                self.d_correction = itl / expect

    def pressure(self) -> Optional[float]:
        """Worst SLO overshoot ratio in the last window (1.0 = exactly at
        SLA); None without a latency observation to judge."""
        m = self.last_window
        if m is None:
            return None
        q = self.config.sla_quantile
        ratios = []
        ttft = m.ttft_signal(q)
        if ttft is not None and self.config.ttft_sla_s > 0:
            ratios.append(ttft / self.config.ttft_sla_s)
        itl = m.itl_signal(q)
        if itl is not None and self.config.itl_sla_s > 0:
            ratios.append(itl / self.config.itl_sla_s)
        return max(ratios) if ratios else None

    # ------------------------- planning --------------------------------

    def compute_replicas(self, num_req: float, isl: float,
                         osl: float) -> tuple:
        """Replica counts meeting the SLAs at the predicted load
        (semantics of ref _compute_replica_requirements:259-355)."""
        cfg = self.config
        interval = cfg.adjustment_interval_s

        # prefill: queueing delay scales ~linearly with backlog, so spend
        # replicas proportional to the TTFT overshoot (capped at 1 —
        # running *better* than SLA must not scale us below the load)
        prefill_tput = (num_req * isl / interval
                        * max(1.0, min(self.p_correction, 4.0)))
        per_prefill = (self.prefill.interpolate_thpt_per_chip(isl)
                       * cfg.prefill_engine_num_chips)
        num_p = math.ceil(prefill_tput / max(per_prefill, 1e-9))

        # decode: tighten the ITL target by the observed interference,
        # then run each chip at the best profiled point meeting it
        corrected_itl = (cfg.itl_sla_s / self.d_correction
                         if self.d_correction > 0 else cfg.itl_sla_s)
        best_tput, _, _ = self.decode.find_best_throughput_per_chip(
            itl_s=corrected_itl, context_length=isl + osl / 2
        )
        decode_tput = num_req * osl / interval
        num_d = math.ceil(
            decode_tput / max(best_tput * cfg.decode_engine_num_chips, 1e-9)
        )

        # live signals: a standing backlog is unserved demand on top of the
        # arrival rate, and an open breaker is capacity that exists on paper
        # only — both demand replicas the rate×latency math can't see
        m = self.last_window
        if m is not None:
            if m.queue_depth > 0 and num_req > 0:
                boost = 1.0 + (cfg.queue_depth_weight * m.queue_depth
                               / max(num_req, 1.0))
                num_p = math.ceil(num_p * min(boost, 4.0))
            if cfg.compensate_breakers and m.breaker_open > 0:
                num_d += int(m.breaker_open)
            if cfg.compensate_preemptions and m.preempt_notices > 0:
                num_d += int(m.preempt_notices)

        num_p = max(num_p, cfg.min_endpoint)
        num_d = max(num_d, cfg.min_endpoint)

        total = (num_p * cfg.prefill_engine_num_chips
                 + num_d * cfg.decode_engine_num_chips)
        if total > cfg.max_chip_budget:
            scale = cfg.max_chip_budget / total
            num_p = max(cfg.min_endpoint, round(num_p * scale))
            num_d = max(cfg.min_endpoint, math.floor(
                (cfg.max_chip_budget
                 - num_p * cfg.prefill_engine_num_chips)
                / cfg.decode_engine_num_chips
            ))
            log.warning("chip budget clamps targets to p=%d d=%d",
                        num_p, num_d)
        return num_p, num_d

    async def _order_degradation(self) -> None:
        """One ladder move per window, pushed through the connector (which
        skips unchanged orders) and broadcast for the aggregator's gauges."""
        if self.ladder is None:
            return
        pressure = self.pressure()
        if pressure is None:
            return
        transition = self.ladder.update(pressure)
        actions = self.ladder.actions()
        if hasattr(self.connector, "set_degradation"):
            await self.connector.set_degradation(actions)
        if transition is not None and hasattr(self.connector,
                                              "publish_event"):
            direction, step = transition
            await self.connector.publish_event({
                "kind": "degradation", "direction": direction, "step": step,
                "level": self.ladder.level, "pressure": pressure,
            })

    async def make_adjustments(self) -> Optional[tuple]:
        """Order degradation for the current pressure, then predict the next
        window and emit replica targets. Returns (num_p, num_d) or None when
        there is no traffic history yet."""
        await self._order_degradation()
        m = self.last_window
        if (m is not None and m.preempt_notices > 0
                and hasattr(self.connector, "publish_event")):
            # surface the proactive-scale trigger so dashboards can line the
            # evacuation up against the replica response
            await self.connector.publish_event({
                "kind": "preemption", "notices": int(m.preempt_notices),
            })
        req = self._pred_req.predict()
        isl = self._pred_isl.predict()
        osl = self._pred_osl.predict()
        if not req or not isl or not osl:
            return None
        num_p, num_d = self.compute_replicas(req, isl, osl)
        if (num_p, num_d) != self.last_targets:
            log.info("scaling targets: prefill=%d decode=%d "
                     "(req=%.1f isl=%.0f osl=%.0f pcorr=%.2f dcorr=%.2f)",
                     num_p, num_d, req, isl, osl,
                     self.p_correction, self.d_correction)
            if hasattr(self.connector, "publish_event"):
                await self.connector.publish_event({
                    "kind": "scale", "prefill": num_p, "decode": num_d,
                })
        await self.connector.scale(self.prefill_component, num_p)
        await self.connector.scale(self.decode_component, num_d)
        self.last_targets = (num_p, num_d)
        return num_p, num_d
