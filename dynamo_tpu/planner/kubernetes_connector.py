"""Kubernetes scaling connector: the planner's targets realised by
merge-patching a graph-deployment custom resource
(ref: components/planner/src/dynamo/planner/kubernetes_connector.py,
kube.py — same contract, re-built on a minimal in-cluster REST client
instead of the kubernetes client package, which this image doesn't ship).

The custom resource (deploy/k8s/crd.yaml) holds one graph of serving
components:

    apiVersion: serving.dynamo-tpu.io/v1alpha1
    kind: TpuGraphDeployment
    spec:
      services:
        backend:  {replicas: 2}
        prefill:  {replicas: 1}

An operator-equivalent reconciler (in-cluster controller or
deploy/scripts/scale_watcher.py pointed at the CR) realises the replica
counts; the planner only writes intent, mirroring the reference's
decoupling. Scaling while the deployment is mid-rollout is skipped — the
same guard the reference applies before patching.
"""

from __future__ import annotations

import asyncio
import json
import os
import ssl
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..utils.logging import get_logger

log = get_logger("planner.k8s")

GROUP = "serving.dynamo-tpu.io"
VERSION = "v1alpha1"
PLURAL = "tpugraphdeployments"

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclass
class KubeConfig:
    """In-cluster API access (the only mode the planner pod needs)."""

    host: str = field(default_factory=lambda: os.environ.get(
        "KUBERNETES_SERVICE_HOST", ""))
    port: str = field(default_factory=lambda: os.environ.get(
        "KUBERNETES_SERVICE_PORT", "443"))
    token: Optional[str] = None
    ca_path: Optional[str] = None
    namespace: Optional[str] = None
    # test/dev override: plain http endpoint, no auth
    base_url: Optional[str] = None

    def resolve(self) -> "KubeConfig":
        if self.base_url is None:
            self.base_url = f"https://{self.host}:{self.port}"
        if self.token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                self.token = f.read().strip()
        if self.ca_path is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            self.ca_path = f"{SA_DIR}/ca.crt"
        if self.namespace is None:
            ns_file = f"{SA_DIR}/namespace"
            if os.path.exists(ns_file):
                with open(ns_file) as f:
                    self.namespace = f.read().strip()
            else:
                self.namespace = "default"
        return self


class K8sApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class KubernetesAPI:
    """Minimal async client for the graph-deployment CR (role of the
    reference's kube.py, without the kubernetes package)."""

    def __init__(self, config: Optional[KubeConfig] = None):
        self.config = (config or KubeConfig()).resolve()
        self._session = None  # lazy shared ClientSession (keep-alive)
        self._ssl: Optional[ssl.SSLContext] = None
        if (self.config.base_url.startswith("https")
                and self.config.ca_path):
            self._ssl = ssl.create_default_context(
                cafile=self.config.ca_path
            )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _headers(self, content_type: str = "application/json") -> dict:
        headers = {"Accept": "application/json",
                   "Content-Type": content_type}
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        return headers

    def _cr_path(self, name: str = "") -> str:
        path = (f"/apis/{GROUP}/{VERSION}/namespaces/"
                f"{self.config.namespace}/{PLURAL}")
        return f"{path}/{name}" if name else path

    async def _request(self, method: str, path: str,
                       body: Optional[dict] = None,
                       content_type: str = "application/json") -> dict:
        import aiohttp

        if self._session is None or self._session.closed:
            # one shared session: per-request sessions would pay a fresh
            # TCP+TLS handshake on every poll of wait_ready
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30)
            )
        async with self._session.request(
            method, self.config.base_url + path,
            headers=self._headers(content_type),
            data=None if body is None else json.dumps(body),
            ssl=self._ssl,
        ) as resp:
            text = await resp.text()
            if resp.status >= 400:
                raise K8sApiError(
                    resp.status,
                    f"k8s API {method} {path} -> {resp.status}: "
                    f"{text[:500]}",
                )
            return json.loads(text) if text else {}

    async def list_graph_deployments(self) -> list:
        out = await self._request("GET", self._cr_path())
        return out.get("items", [])

    async def get_graph_deployment(
        self, name: Optional[str] = None,
    ) -> Optional[dict]:
        """The named CR, or the single CR in the namespace (the common
        one-graph-per-namespace deployment shape)."""
        if name:
            try:
                return await self._request("GET", self._cr_path(name))
            except K8sApiError as exc:
                if exc.status == 404:
                    return None
                raise  # 403 etc. is a real error, not "missing CR"
        items = await self.list_graph_deployments()
        if not items:
            return None
        if len(items) > 1:
            log.warning("multiple graph deployments in %s — using %s",
                        self.config.namespace,
                        items[0]["metadata"]["name"])
        return items[0]

    async def patch_service_replicas(
        self, name: str, component: str, replicas: int,
    ) -> None:
        await self._request(
            "PATCH", self._cr_path(name),
            body={"spec": {"services": {component: {
                "replicas": int(replicas)}}}},
            content_type="application/merge-patch+json",
        )

    async def is_ready(self, deployment: dict) -> bool:
        """Rollout settled: every service's observed replicas match spec
        (the reference gates on the operator's ready condition; our
        reconciler mirrors counts into status.services)."""
        status = deployment.get("status", {})
        conditions = status.get("conditions", [])
        for cond in conditions:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        observed = status.get("services", {})
        spec = deployment.get("spec", {}).get("services", {})
        if not observed:
            return True  # no status reported yet — don't wedge scaling
        return all(
            observed.get(svc, {}).get("replicas")
            == spec.get(svc, {}).get("replicas")
            for svc in spec
        )

    async def wait_ready(self, name: str, timeout_s: float = 300.0,
                         poll_s: float = 2.0) -> bool:
        deadline = asyncio.get_running_loop().time() + timeout_s
        while asyncio.get_running_loop().time() < deadline:
            dep = await self.get_graph_deployment(name)
            if dep is not None and await self.is_ready(dep):
                return True
            await asyncio.sleep(poll_s)
        return False


class KubernetesConnector:
    """VirtualConnector-shaped scaling intent writer backed by the CR
    (the planner calls ``scale``; the cluster reconciler does the rest)."""

    def __init__(self, api: Optional[KubernetesAPI] = None,
                 deployment_name: Optional[str] = None,
                 blocking: bool = False):
        self.api = api or KubernetesAPI()
        self.deployment_name = deployment_name
        self.blocking = blocking
        self.decision_count = 0

    async def _deployment(self) -> dict:
        dep = await self.api.get_graph_deployment(self.deployment_name)
        if dep is None:
            raise RuntimeError(
                f"graph deployment "
                f"{self.deployment_name or '(any)'} not found in "
                f"{self.api.config.namespace}"
            )
        return dep

    async def scale(self, component: str, replicas: int) -> None:
        dep = await self._deployment()
        name = dep["metadata"]["name"]
        services = dep.get("spec", {}).get("services", {})
        if component not in services:
            raise ValueError(
                f"component {component!r} not in deployment {name} "
                f"(services: {sorted(services)})"
            )
        if not await self.api.is_ready(dep):
            # mid-rollout: piling a new target onto an unsettled rollout
            # thrashes pods (the reference applies the same guard)
            log.warning("deployment %s mid-rollout — skipping scale of "
                        "%s to %d", name, component, replicas)
            return
        current = services[component].get("replicas", 1)
        if current == int(replicas):
            return
        self.decision_count += 1
        await self.api.patch_service_replicas(name, component, replicas)
        log.info("scaled %s/%s: %d -> %d", name, component, current,
                 replicas)
        if self.blocking:
            await self.api.wait_ready(name)

    async def read_target(self, component: str) -> Optional[int]:
        dep = await self.api.get_graph_deployment(self.deployment_name)
        if dep is None:
            return None
        svc = dep.get("spec", {}).get("services", {}).get(component)
        return None if svc is None else int(svc.get("replicas", 1))
