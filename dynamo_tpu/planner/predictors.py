"""Load predictors (ref: components/planner/src/dynamo/planner/utils/
load_predictor.py — Constant:66, ARIMA:79, Prophet:119).

Each predictor consumes one observation per adjustment window and predicts
the next window's value. The ARIMA/Prophet roles are covered by a
least-squares AR(p) model — no heavyweight stats deps in the serving image.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Deque, Optional

import numpy as np


def _clean(value) -> Optional[float]:
    """None for unusable observations (None/NaN/inf — the shapes a startup
    gap or a store outage window produces), else the float value."""
    if value is None:
        return None
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


class ConstantPredictor:
    """Next value = last observed value."""

    def __init__(self) -> None:
        self._last: Optional[float] = None

    def observe(self, value: float) -> None:
        v = _clean(value)
        if v is not None:
            self._last = v

    def predict(self) -> Optional[float]:
        return self._last


class MovingAveragePredictor:
    """Next value = mean of the last ``window`` observations."""

    def __init__(self, window: int = 8) -> None:
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        v = _clean(value)
        if v is not None:
            self._buf.append(v)

    def predict(self) -> Optional[float]:
        return float(np.mean(self._buf)) if self._buf else None


class ARPredictor:
    """AR(p) one-step-ahead forecast fitted by least squares over a sliding
    history. Captures trends and short periodicities (the ARIMA role);
    falls back to the mean until 2p+1 observations exist.

    Invalid observations (None/NaN/inf) are dropped instead of entering the
    history: one empty adjustment window during startup or a store outage
    must not poison every subsequent lstsq fit with NaN."""

    def __init__(self, order: int = 4, history: int = 64) -> None:
        self.order = order
        self._buf: Deque[float] = deque(maxlen=history)
        self.num_dropped = 0

    def observe(self, value: float) -> None:
        v = _clean(value)
        if v is None:
            self.num_dropped += 1
            return
        self._buf.append(v)

    def predict(self) -> Optional[float]:
        if not self._buf:
            return None
        y = np.asarray(self._buf, np.float64)
        p = self.order
        if len(y) < 2 * p + 1:
            return float(y.mean())
        # rows: y[t] ~ [1, y[t-1], ..., y[t-p]]
        X = np.stack(
            [np.ones(len(y) - p)]
            + [y[p - j - 1: len(y) - j - 1] for j in range(p)],
            axis=1,
        )
        coef, *_ = np.linalg.lstsq(X, y[p:], rcond=None)
        nxt = coef[0] + float(coef[1:] @ y[-1: -p - 1: -1])
        if not math.isfinite(nxt):
            return float(y.mean())
        # a degenerate fit must not drive scaling negative
        return max(0.0, float(nxt))
