"""Orchestrator: realise the planner's scaling intent against a live
deployment (ref: the operator role of kube.rs + DynaServe's unified P/D
role reassignment).

Watches ``planner/{ns}/target/{component}`` (poll-based, like
deploy/scripts/scale_watcher.py — a store flap degrades to staleness, not a
crash) and reconciles the worker pool toward it. Capacity moves are
realised cheapest-first:

1. **Role flips** — when one role is over target and the other under, a
   worker is flipped instead of paying a stop + cold spawn: the pool drains
   the worker's current endpoint (deregister → in-flight join → stragglers
   stopped so Migration carries them to a peer with byte-exact token
   parity) and re-serves the same process under the other component.
2. **Spawns / stops** — the remaining deltas, clamped to the chip budget.

The pool is anything implementing the small ``WorkerPool`` surface below:
the simulated cluster (mocker/cluster.py) in tests, a process-spawning pool
in deployments.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol

from .. import tracing
from ..utils.logging import get_logger

log = get_logger("planner.orchestrator")


class WorkerPool(Protocol):
    """What the orchestrator needs from a deployment."""

    def workers(self, component: str) -> List[int]:
        """Live worker ids currently serving ``component``."""
        ...

    async def spawn(self, component: str) -> int:
        """Start a new worker on ``component``; returns its id."""
        ...

    async def stop(self, worker_id: int) -> None:
        """Gracefully drain + stop a worker (in-flight streams migrate)."""
        ...

    async def flip(self, worker_id: int, component: str) -> None:
        """Drain a worker off its current component and re-serve it on
        ``component`` — same process, zero dropped streams."""
        ...


@dataclass
class OrchestratorStats:
    num_flips: int = 0
    num_spawns: int = 0
    num_stops: int = 0
    num_cycles: int = 0


class Orchestrator:
    def __init__(
        self,
        store,
        pool: WorkerPool,
        namespace: str = "dynamo",
        prefill_component: str = "prefill",
        decode_component: str = "backend",
        poll_s: float = 0.5,
        max_chip_budget: Optional[int] = None,
        flip_enabled: bool = True,
    ):
        self.store = store
        self.pool = pool
        self.namespace = namespace
        self.prefill_component = prefill_component
        self.decode_component = decode_component
        self.poll_s = poll_s
        self.max_chip_budget = max_chip_budget
        self.flip_enabled = flip_enabled
        self.stats = OrchestratorStats()
        self._task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()

    # --------------------------- intent --------------------------------

    def _target_key(self, component: str) -> str:
        return f"planner/{self.namespace}/target/{component}"

    async def read_target(self, component: str) -> Optional[int]:
        raw = await self.store.get(self._target_key(component))
        if raw is None:
            return None
        try:
            return int(json.loads(raw)["replicas"])
        except Exception:
            log.warning("malformed target for %s: %r", component, raw)
            return None

    # ------------------------- reconciliation ---------------------------

    async def reconcile(self) -> Dict[str, int]:
        """One convergence step toward the recorded targets. Returns the
        realised move counts (all zero when already converged)."""
        async with self._lock:
            return await self._reconcile_locked()

    async def _reconcile_locked(self) -> Dict[str, int]:
        moves = {"flips": 0, "spawns": 0, "stops": 0}
        p_comp, d_comp = self.prefill_component, self.decode_component
        targets = {}
        for comp in (p_comp, d_comp):
            t = await self.read_target(comp)
            if t is not None:
                targets[comp] = max(0, t)
        if not targets:
            return moves
        if self.max_chip_budget is not None:
            total = sum(targets.values())
            if total > self.max_chip_budget:
                # defensive re-clamp: a malformed/stale record must not
                # make the orchestrator exceed the budget the planner holds
                scale = self.max_chip_budget / total
                targets = {c: max(1, int(t * scale))
                           for c, t in targets.items()}

        deltas = {c: t - len(self.pool.workers(c))
                  for c, t in targets.items()}
        self.stats.num_cycles += 1

        # capacity moves between roles are flips, not stop+spawn
        if self.flip_enabled and p_comp in deltas and d_comp in deltas:
            for need, donor in ((p_comp, d_comp), (d_comp, p_comp)):
                while deltas.get(need, 0) > 0 and deltas.get(donor, 0) < 0:
                    candidates = self.pool.workers(donor)
                    if not candidates:
                        break
                    wid = candidates[-1]  # newest first: oldest keep their role
                    await self._flip(wid, donor, need)
                    deltas[need] -= 1
                    deltas[donor] += 1
                    moves["flips"] += 1

        for comp, delta in deltas.items():
            while delta > 0:
                await self._spawn(comp)
                delta -= 1
                moves["spawns"] += 1
            while delta < 0:
                candidates = self.pool.workers(comp)
                if not candidates:
                    break
                await self._stop(candidates[-1], comp)
                delta += 1
                moves["stops"] += 1
        return moves

    async def _flip(self, wid: int, donor: str, need: str) -> None:
        span = tracing.get_tracer().start_span(
            "orchestrator.flip", root=True,
            attrs={"worker": wid, "from": donor, "to": need},
        )
        try:
            log.info("flipping worker %d: %s -> %s", wid, donor, need)
            await self.pool.flip(wid, need)
            self.stats.num_flips += 1
        except Exception:
            span.set_status("error", "flip_failed")
            raise
        finally:
            span.end()

    async def _spawn(self, comp: str) -> None:
        span = tracing.get_tracer().start_span(
            "orchestrator.spawn", root=True, attrs={"component": comp},
        )
        try:
            wid = await self.pool.spawn(comp)
            log.info("spawned worker %d on %s", wid, comp)
            self.stats.num_spawns += 1
        except Exception:
            span.set_status("error", "spawn_failed")
            raise
        finally:
            span.end()

    async def _stop(self, wid: int, comp: str) -> None:
        span = tracing.get_tracer().start_span(
            "orchestrator.stop", root=True,
            attrs={"worker": wid, "component": comp},
        )
        try:
            log.info("stopping worker %d on %s", wid, comp)
            await self.pool.stop(wid)
            self.stats.num_stops += 1
        except Exception:
            span.set_status("error", "stop_failed")
            raise
        finally:
            span.end()

    # --------------------------- lifecycle ------------------------------

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.reconcile()
            except Exception:
                # a failed move (worker died mid-flip, store blip) retries
                # next cycle against fresh pool state
                log.exception("reconcile failed — retrying next cycle")
            await asyncio.sleep(self.poll_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
