"""Pre-profiled performance interpolation (ref: components/planner/src/
dynamo/planner/utils/perf_interpolation.py — PrefillInterpolator,
DecodeInterpolator).

The SLA profiler sweeps the engine offline and records:
- prefill: ISL → TTFT and throughput/chip (1D curves);
- decode: (kv_usage, context_length) → ITL and throughput/chip (2D surface).

The planner inverts these at runtime: "what per-chip throughput can I run at
while keeping ITL under the SLA at this context length?" Linear
interpolation over the profiled grid — smooth enough for scaling decisions,
no scipy dependency.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np


class PrefillInterpolator:
    """1D ISL → (ttft_s, throughput_per_chip) interpolation."""

    def __init__(self, isl: Sequence[float], ttft_s: Sequence[float],
                 thpt_per_chip: Sequence[float]):
        order = np.argsort(isl)
        self.isl = np.asarray(isl, np.float64)[order]
        self.ttft = np.asarray(ttft_s, np.float64)[order]
        self.thpt = np.asarray(thpt_per_chip, np.float64)[order]

    @classmethod
    def from_profile(cls, profile: Dict) -> "PrefillInterpolator":
        return cls(profile["prefill_isl"], profile["prefill_ttft_s"],
                   profile["prefill_thpt_per_chip"])

    def interpolate_ttft(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.ttft))

    def interpolate_thpt_per_chip(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.thpt))


class DecodeInterpolator:
    """2D (kv_usage ∈ [0,1], context_length) → (itl_s, throughput/chip).

    Profiled as scattered points; queried either directly (bilinear over a
    gridded fit) or inversely via :meth:`find_best_throughput_per_chip`.
    """

    def __init__(self, kv_usage: Sequence[float],
                 context_length: Sequence[float],
                 itl_s: Sequence[float],
                 thpt_per_chip: Sequence[float],
                 resolution: int = 64):
        x = np.asarray(kv_usage, np.float64)
        y = np.asarray(context_length, np.float64)
        self.itl = np.asarray(itl_s, np.float64)
        self.thpt = np.asarray(thpt_per_chip, np.float64)
        self.points = np.stack([x, y], axis=1)
        self.xi = np.linspace(0.0, 1.0, resolution)
        self.yi = np.linspace(float(y.min()), float(y.max()), resolution)

    @classmethod
    def from_profile(cls, profile: Dict) -> "DecodeInterpolator":
        return cls(profile["decode_kv_usage"],
                   profile["decode_context_length"],
                   profile["decode_itl_s"],
                   profile["decode_thpt_per_chip"])

    def _idw(self, values: np.ndarray, x: float, y: float) -> float:
        """Inverse-distance-weighted estimate at (x, y) — robust on the
        scattered profile points without scipy's Delaunay machinery."""
        span_y = max(1.0, float(self.yi[-1] - self.yi[0]))
        d2 = ((self.points[:, 0] - x) ** 2
              + ((self.points[:, 1] - y) / span_y) ** 2)
        near = d2 < 1e-12
        if near.any():
            return float(values[near][0])
        w = 1.0 / d2
        return float((w * values).sum() / w.sum())

    def interpolate_itl(self, kv_usage: float, context_length: float) -> float:
        return self._idw(self.itl, min(max(kv_usage, 0.0), 1.0),
                         context_length)

    def interpolate_thpt_per_chip(self, kv_usage: float,
                                  context_length: float) -> float:
        return self._idw(self.thpt, min(max(kv_usage, 0.0), 1.0),
                         context_length)

    def find_best_throughput_per_chip(
        self, itl_s: float, context_length: float
    ) -> Tuple[float, float, float]:
        """Max throughput/chip whose interpolated ITL stays ≤ the target at
        this context length. Returns (thpt_per_chip, kv_usage, itl_s);
        falls back to the lowest-ITL operating point when nothing meets the
        SLA (best effort, same shape as the reference's inverse lookup)."""
        best = None
        fallback = None
        for x in self.xi:
            itl = self.interpolate_itl(float(x), context_length)
            thpt = self.interpolate_thpt_per_chip(float(x), context_length)
            cand = (thpt, float(x), itl)
            if fallback is None or itl < fallback[2]:
                fallback = cand
            if itl <= itl_s and (best is None or thpt > best[0]):
                best = cand
        return best if best is not None else fallback
