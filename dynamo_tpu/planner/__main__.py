"""Planner process: subscribe to frontend window stats, emit scaling targets
(ref: components/planner/src/dynamo/planner — start_sla_planner).

    python -m dynamo_tpu.planner --profile profile.json \
        --ttft 0.5 --itl 0.05 --adjustment-interval 30

The profile file carries the SLA profiler's curves (see
``dynamo_tpu.planner.interpolation`` for the keys). Targets are written to
the store under ``planner/{namespace}/target/*`` (virtual connector); an
orchestrator realises them.
"""

from __future__ import annotations

import argparse
import asyncio
import json

import msgpack

from ..runtime.component import DistributedRuntime
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger
from .connector import VirtualConnector
from .core import Planner, PlannerConfig, WindowMetrics
from .interpolation import DecodeInterpolator, PrefillInterpolator

log = get_logger("planner.main")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu SLA planner")
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--profile", required=True,
                   help="JSON file with profiled perf curves")
    p.add_argument("--ttft", type=float, default=0.5, help="TTFT SLA (s)")
    p.add_argument("--itl", type=float, default=0.05, help="ITL SLA (s)")
    p.add_argument("--adjustment-interval", type=float, default=30.0)
    p.add_argument("--prefill-chips", type=int, default=1)
    p.add_argument("--decode-chips", type=int, default=1)
    p.add_argument("--max-chip-budget", type=int, default=64)
    p.add_argument("--min-endpoint", type=int, default=1)
    p.add_argument("--prefill-component", default="prefill")
    p.add_argument("--decode-component", default="backend")
    p.add_argument(
        "--connector", default="virtual",
        choices=["virtual", "kubernetes"],
        help="virtual: write targets into the store (an orchestrator like "
             "scale_watcher realises them); kubernetes: merge-patch the "
             "TpuGraphDeployment CR in-cluster (ref: kubernetes_connector)",
    )
    p.add_argument("--k8s-deployment", default=None,
                   help="TpuGraphDeployment name (default: the single CR "
                        "in the pod's namespace)")
    return p.parse_args(argv)


async def run_planner(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(config)

    with open(args.profile) as f:
        profile = json.load(f)
    if args.connector == "kubernetes":
        from .kubernetes_connector import KubernetesConnector

        connector = KubernetesConnector(deployment_name=args.k8s_deployment)
    else:
        connector = VirtualConnector(runtime.store,
                                     namespace=runtime.namespace().name)
    planner = Planner(
        PlannerConfig(
            ttft_sla_s=args.ttft,
            itl_sla_s=args.itl,
            adjustment_interval_s=args.adjustment_interval,
            prefill_engine_num_chips=args.prefill_chips,
            decode_engine_num_chips=args.decode_chips,
            min_endpoint=args.min_endpoint,
            max_chip_budget=args.max_chip_budget,
        ),
        PrefillInterpolator.from_profile(profile),
        DecodeInterpolator.from_profile(profile),
        connector,
        prefill_component=args.prefill_component,
        decode_component=args.decode_component,
    )

    subject = f"{runtime.namespace().name}/frontend_stats"
    sub = await runtime.store.subscribe(subject)

    async def _ingest():
        nonlocal sub
        while True:
            event = await sub.next()
            if event is None or event["event"] == "dropped":
                log.warning("frontend_stats subscription lost — resubscribing")
                await sub.cancel()
                while True:  # outlast a store reconnect window
                    try:
                        sub = await runtime.store.subscribe(subject)
                        break
                    except Exception:
                        log.exception("stats resubscribe failed — retrying")
                        await asyncio.sleep(0.5)
                continue
            if event["event"] != "msg":
                continue
            try:
                win = msgpack.unpackb(event["value"])
                planner.observe(WindowMetrics(
                    num_requests=win.get("num_requests") or 0,
                    isl_avg=win.get("isl_avg") or 0,
                    osl_avg=win.get("osl_avg") or 0,
                    ttft_avg_s=win.get("ttft_avg_s"),
                    itl_avg_s=win.get("itl_avg_s"),
                ))
            except Exception:
                log.exception("bad frontend_stats payload")

    ingest_task = asyncio.create_task(_ingest())
    log.info("planner running (interval=%ss)", args.adjustment_interval)
    try:
        while True:
            await asyncio.sleep(args.adjustment_interval)
            try:
                await planner.make_adjustments()
            except Exception:
                # a transient connector failure (apiserver 5xx, network
                # blip) must not kill the planner — next window retries
                log.exception("adjustment failed — retrying next window")
    finally:
        ingest_task.cancel()
        await runtime.shutdown()


def main(argv=None) -> None:
    asyncio.run(run_planner(parse_args(argv)))


if __name__ == "__main__":
    main()
