"""Planner process: subscribe to frontend window stats + aggregator
signals, order degradation, emit scaling targets
(ref: components/planner/src/dynamo/planner — start_sla_planner).

    python -m dynamo_tpu.planner --profile profile.json \
        --ttft 0.5 --itl 0.05 --adjustment-interval 30

The profile file carries the SLA profiler's curves (see
``dynamo_tpu.planner.interpolation`` for the keys). Targets are written to
the store under ``planner/{namespace}/target/*`` (virtual connector); an
orchestrator (``dynamo_tpu.planner.orchestrator`` against a worker pool, or
deploy/scripts/scale_watcher.py) realises them. Degradation orders land at
``planner/{namespace}/degradation`` for frontends/workers to apply.
"""

from __future__ import annotations

import argparse
import asyncio
import json

import msgpack

from ..runtime.component import DistributedRuntime
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger
from .connector import VirtualConnector
from .core import Planner, PlannerConfig, WindowMetrics
from .degradation import DegradationConfig
from .interpolation import DecodeInterpolator, PrefillInterpolator

log = get_logger("planner.main")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu SLA planner")
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--profile", required=True,
                   help="JSON file with profiled perf curves")
    p.add_argument("--ttft", type=float, default=0.5, help="TTFT SLA (s)")
    p.add_argument("--itl", type=float, default=0.05, help="ITL SLA (s)")
    p.add_argument("--adjustment-interval", type=float, default=30.0)
    p.add_argument("--prefill-chips", type=int, default=1)
    p.add_argument("--decode-chips", type=int, default=1)
    p.add_argument("--max-chip-budget", type=int, default=64)
    p.add_argument("--min-endpoint", type=int, default=1)
    p.add_argument("--prefill-component", default="prefill")
    p.add_argument("--decode-component", default="backend")
    p.add_argument(
        "--sla-quantile", default=None, choices=["p99", "p50", "avg"],
        help="latency statistic the SLAs are enforced on (default "
             "DYNTPU_PLANNER_SLA_QUANTILE, 'p99'; 'avg' restores the "
             "pre-percentile behavior)",
    )
    p.add_argument(
        "--no-degradation", action="store_true",
        help="disable the graceful-degradation ladder (shed low tiers -> "
             "clamp spec_k -> tighten prefill chunking before scaling)",
    )
    p.add_argument(
        "--engage-ratio", type=float, default=None,
        help="SLO overshoot ratio at/above which the next ladder step "
             "engages (default DYNTPU_PLANNER_ENGAGE_RATIO, 1.5)",
    )
    p.add_argument(
        "--release-ratio", type=float, default=None,
        help="SLO ratio at/below which the last ladder step releases "
             "(default DYNTPU_PLANNER_RELEASE_RATIO, 1.0)",
    )
    p.add_argument(
        "--shed-tier", type=int, default=None,
        help="min admitted request tier while shed_low_tier is engaged "
             "(default DYNTPU_PLANNER_SHED_TIER, 1)",
    )
    p.add_argument(
        "--spec-k-clamp", type=int, default=None,
        help="spec_k ceiling while clamp_spec_k is engaged "
             "(default DYNTPU_PLANNER_SPEC_K_CLAMP, 1)",
    )
    p.add_argument(
        "--chunk-clamp-tokens", type=int, default=None,
        help="prefill_chunk_tokens ceiling while tighten_chunking is "
             "engaged (default DYNTPU_PLANNER_CHUNK_CLAMP_TOKENS, 256)",
    )
    p.add_argument(
        "--connector", default="virtual",
        choices=["virtual", "kubernetes"],
        help="virtual: write targets into the store (an orchestrator like "
             "scale_watcher realises them); kubernetes: merge-patch the "
             "TpuGraphDeployment CR in-cluster (ref: kubernetes_connector)",
    )
    p.add_argument("--k8s-deployment", default=None,
                   help="TpuGraphDeployment name (default: the single CR "
                        "in the pod's namespace)")
    return p.parse_args(argv)


async def run_planner(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(config)

    with open(args.profile) as f:
        profile = json.load(f)
    if args.connector == "kubernetes":
        from .kubernetes_connector import KubernetesConnector

        connector = KubernetesConnector(deployment_name=args.k8s_deployment)
    else:
        connector = VirtualConnector(runtime.store,
                                     namespace=runtime.namespace().name)

    def _or(cli, cfg_val):
        return cfg_val if cli is None else cli

    degradation = None
    if config.planner_degradation_enabled and not args.no_degradation:
        degradation = DegradationConfig(
            engage_ratio=_or(args.engage_ratio, config.planner_engage_ratio),
            release_ratio=_or(args.release_ratio,
                              config.planner_release_ratio),
            shed_tier=_or(args.shed_tier, config.planner_shed_tier),
            spec_k_clamp=_or(args.spec_k_clamp,
                             config.planner_spec_k_clamp),
            chunk_clamp_tokens=_or(args.chunk_clamp_tokens,
                                   config.planner_chunk_clamp_tokens),
        )
    planner = Planner(
        PlannerConfig(
            ttft_sla_s=args.ttft,
            itl_sla_s=args.itl,
            adjustment_interval_s=args.adjustment_interval,
            prefill_engine_num_chips=args.prefill_chips,
            decode_engine_num_chips=args.decode_chips,
            min_endpoint=args.min_endpoint,
            max_chip_budget=args.max_chip_budget,
            sla_quantile=_or(args.sla_quantile,
                             config.planner_sla_quantile),
            degradation=degradation,
        ),
        PrefillInterpolator.from_profile(profile),
        DecodeInterpolator.from_profile(profile),
        connector,
        prefill_component=args.prefill_component,
        decode_component=args.decode_component,
    )

    ns = runtime.namespace().name
    # latest aggregator-published signals, merged into each frontend window
    signals = {"queue_depth": 0, "spec_acceptance": None,
               "preempt_notices": 0}

    def _window_from(win: dict) -> WindowMetrics:
        return WindowMetrics(
            num_requests=win.get("num_requests") or 0,
            isl_avg=win.get("isl_avg") or 0,
            osl_avg=win.get("osl_avg") or 0,
            ttft_avg_s=win.get("ttft_avg_s"),
            itl_avg_s=win.get("itl_avg_s"),
            ttft_p50_s=win.get("ttft_p50_s"),
            ttft_p99_s=win.get("ttft_p99_s"),
            itl_p50_s=win.get("itl_p50_s"),
            itl_p99_s=win.get("itl_p99_s"),
            # frontend admission backlog + worker queues (aggregator feed)
            queue_depth=((win.get("queue_depth") or 0)
                         + (signals["queue_depth"] or 0)),
            breaker_open=win.get("breaker_open") or 0,
            spec_acceptance=(win.get("spec_acceptance")
                             if win.get("spec_acceptance") is not None
                             else signals["spec_acceptance"]),
            preempt_notices=signals["preempt_notices"] or 0,
        )

    async def _subscribe_loop(subject, handler):
        sub = await runtime.store.subscribe(subject)
        while True:
            event = await sub.next()
            if event is None or event["event"] == "dropped":
                log.warning("%s subscription lost — resubscribing", subject)
                await sub.cancel()
                while True:  # outlast a store reconnect window
                    try:
                        sub = await runtime.store.subscribe(subject)
                        break
                    except Exception:
                        log.exception("resubscribe failed — retrying")
                        await asyncio.sleep(0.5)
                continue
            if event["event"] != "msg":
                continue
            try:
                handler(msgpack.unpackb(event["value"]))
            except Exception:
                log.exception("bad payload on %s", subject)

    def _on_window(win: dict) -> None:
        planner.observe(_window_from(win))

    def _on_signals(payload: dict) -> None:
        signals["queue_depth"] = payload.get("queue_depth") or 0
        signals["spec_acceptance"] = payload.get("spec_acceptance")
        signals["preempt_notices"] = payload.get("preempt_notices") or 0

    tasks = [
        asyncio.create_task(
            _subscribe_loop(f"{ns}/frontend_stats", _on_window)),
        asyncio.create_task(
            _subscribe_loop(f"{ns}/planner_signals", _on_signals)),
    ]
    log.info("planner running (interval=%ss quantile=%s degradation=%s)",
             args.adjustment_interval, planner.config.sla_quantile,
             "on" if degradation is not None else "off")
    try:
        while True:
            await asyncio.sleep(args.adjustment_interval)
            try:
                await planner.make_adjustments()
            except Exception:
                # a transient connector failure (apiserver 5xx, network
                # blip) must not kill the planner — next window retries
                log.exception("adjustment failed — retrying next window")
    finally:
        for t in tasks:
            t.cancel()
        await runtime.shutdown()


def main(argv=None) -> None:
    asyncio.run(run_planner(parse_args(argv)))


if __name__ == "__main__":
    main()
