"""Continuous-batching scheduler with paged-block accounting.

Faithful to the vLLM semantics the reference encodes compactly in its mocker
(ref: lib/llm/src/mocker/scheduler.rs:240 and kv_manager.rs:507): waiting and
running queues, a per-step token budget with chunked prefill, a free-block
watermark on admission, LRU eviction of sealed (hash-keyed) blocks, prefix
caching by chained sequence hash, and preemption-by-recompute when the pool
runs dry. KV events (stored/removed, ref: lib/llm/src/kv_router/
protocols.rs) are emitted for the router's radix indexer.

Token/KV invariants:
- ``num_computed`` = tokens whose KV is written to the cache.
- During prefill, chunks advance ``num_computed`` through the prompt; the
  chunk that completes the prompt also samples the first output token.
- During decode, the step feeds ``all_tokens[num_computed]`` (writing its KV)
  and samples the next token, so ``total = num_computed + 1`` between steps.
"""

from __future__ import annotations

import enum
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence as Seq, Tuple

from ..tokens import TokenBlockSequence
from ..utils.hotpath import hot_path
from ..utils.logging import get_logger
from .config import EngineConfig

log = get_logger("engine.scheduler")

TRASH_BLOCK = 0  # physical block 0 absorbs padding writes; never allocated


class KvEvent:
    """KV cache event for the router indexer (stored / removed)."""

    __slots__ = ("kind", "blocks")

    def __init__(self, kind: str, blocks: List[dict]):
        self.kind = kind      # "stored" | "removed" | "cleared"
        self.blocks = blocks  # [{"seq_hash", "parent", "block_hash"}] / hashes

    def to_dict(self) -> dict:
        return {"kind": self.kind, "blocks": self.blocks}


class BlockPool:
    """Reference-counted physical block pool with hash-keyed reuse.

    Sealed blocks (content-complete, keyed by chained sequence hash) become
    *evictable* instead of free when their refcount drops to zero, forming the
    prefix cache; eviction is LRU (ref: mocker/evictor.rs).
    """

    def __init__(self, num_blocks: int,
                 on_event: Optional[Callable[[KvEvent], None]] = None):
        self.num_blocks = num_blocks
        self._free: Deque[int] = deque(range(1, num_blocks))  # 0 = trash
        self._ref: Dict[int, int] = {}
        self._hash_of: Dict[int, int] = {}         # block -> seq_hash
        self._parent_of: Dict[int, Optional[int]] = {}
        self._cached: Dict[int, int] = {}           # seq_hash -> block
        self._evictable: "OrderedDict[int, int]" = OrderedDict()  # block -> hash
        self.on_event = on_event

    # -- capacity --

    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._evictable)

    @property
    def usage(self) -> float:
        usable = self.num_blocks - 1
        return 1.0 - self.num_free / usable if usable else 1.0

    # -- allocation --

    def allocate(self) -> Optional[int]:
        if self._free:
            bid = self._free.popleft()
            self._ref[bid] = 1
            return bid
        if self._evictable:
            bid, seq_hash = self._evictable.popitem(last=False)  # LRU
            self._cached.pop(seq_hash, None)
            self._emit(KvEvent("removed", [seq_hash]))
            self._hash_of.pop(bid, None)
            self._parent_of.pop(bid, None)
            self._ref[bid] = 1
            return bid
        return None

    def lookup(self, seq_hash: int) -> Optional[int]:
        """Prefix-cache hit: reuse a sealed block by sequence hash."""
        bid = self._cached.get(seq_hash)
        if bid is None:
            return None
        if bid in self._evictable:
            del self._evictable[bid]
            self._ref[bid] = 1
        else:
            self._ref[bid] += 1
        return bid

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._cached

    def adopt(self, seq_hash: int, block_hash: int,
              parent: Optional[int]) -> Optional[int]:
        """Allocate a block and register it as sealed WITHOUT any sequence
        owning it — the KVBM onboard path (G2/G3 → G1). Returned with
        refcount 1 so it cannot be evicted while the caller injects the KV;
        ``release_adopted`` afterwards makes it an evictable cache hit."""
        if seq_hash in self._cached:
            return None
        bid = self.allocate()
        if bid is None:
            return None
        self.seal(bid, seq_hash, block_hash, parent)
        return bid

    def release_adopted(self, bid: int) -> None:
        self.decref(bid)  # refcount 0 + sealed → evictable (cached)

    def discard_adopted(self, bid: int) -> None:
        """Back out an ``adopt`` whose KV injection failed: unregister the
        hash so the block can never be served as a prefix hit, then free it.
        (Releasing it normally would poison the prefix cache with blocks
        whose KV was never written.)"""
        seq_hash = self._hash_of.pop(bid, None)
        self._parent_of.pop(bid, None)
        if seq_hash is not None and self._cached.get(seq_hash) == bid:
            del self._cached[seq_hash]
            self._emit(KvEvent("removed", [seq_hash]))
        self._ref.pop(bid, None)
        self._free.append(bid)

    def incref(self, bid: int) -> None:
        self._ref[bid] += 1

    def decref(self, bid: int) -> None:
        self._ref[bid] -= 1
        if self._ref[bid] > 0:
            return
        del self._ref[bid]
        seq_hash = self._hash_of.get(bid)
        if seq_hash is not None and self._cached.get(seq_hash) == bid:
            self._evictable[bid] = seq_hash   # keep content for reuse
        else:
            self._free.append(bid)

    def seal(self, bid: int, seq_hash: int, block_hash: int,
             parent: Optional[int]) -> None:
        """Register a content-complete block for prefix reuse."""
        if seq_hash in self._cached:
            return  # identical content already cached under another block
        self._hash_of[bid] = seq_hash
        self._parent_of[bid] = parent
        self._cached[seq_hash] = bid
        self._emit(KvEvent("stored", [
            {"seq_hash": seq_hash, "block_hash": block_hash,
             "parent": parent, "block_id": bid}
        ]))

    def clear(self) -> None:
        """Drop the prefix cache. Blocks still referenced by running
        sequences stay allocated (their hash registrations are removed, so
        on release they are freed rather than kept for reuse); evictable
        blocks return to the free list."""
        for bid in self._evictable:
            self._free.append(bid)
        self._evictable.clear()
        self._cached.clear()
        self._hash_of.clear()
        self._parent_of.clear()
        self._emit(KvEvent("cleared", []))

    def _emit(self, event: KvEvent) -> None:
        if self.on_event is not None:
            self.on_event(event)


class SeqStatus(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    RUNNING = "running"
    # parked for live KV evacuation (runtime/preemption.py): no new
    # windows are planned for the seat, its blocks stay pinned until the
    # transfer lands, and it is not a recompute-preemption victim
    EVACUATING = "evacuating"
    FINISHED = "finished"


@dataclass
class SchedSeq:
    """Scheduler-side state of one sequence."""

    seq_id: str
    prompt_ids: List[int]
    max_tokens: int
    eos_token_ids: frozenset
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = -1          # -1 = unseeded (engine rng)
    # multimodal: placeholder positions + their embedding rows [N, D]
    mm_positions: Optional[list] = None
    mm_embeddings: Optional[object] = None
    arrival: float = field(default_factory=time.monotonic)
    # tracing stamps (monotonic): first time a prefill chunk was scheduled,
    # and when the first output token was emitted — the engine derives the
    # worker.queue / engine.prefill / engine.decode span windows from these
    t_scheduled: Optional[float] = None
    t_first_token: Optional[float] = None
    status: SeqStatus = SeqStatus.WAITING
    output_ids: List[int] = field(default_factory=list)
    block_table: List[int] = field(default_factory=list)
    num_computed: int = 0
    num_sealed_blocks: int = 0
    finish_reason: Optional[str] = None
    token_seq: Optional[TokenBlockSequence] = None
    preemptions: int = 0
    # disagg: keep blocks alive after finish until the KV is extracted
    # (prefill worker side; released via Scheduler.release_held)
    hold_blocks: bool = False
    # disagg: reservation epoch stamped by EngineCore.reserve_sequence —
    # a transfer carrying a stale epoch must never scatter into these
    # blocks (they may have been recycled to another request)
    kv_epoch: int = 0
    # ---- pipelined (run-ahead) serving state ----
    # device token-ring slot (-1 = unassigned); see model.raw_decode_window_fn
    slot: int = -1
    # slot held when this seq was last preempted (engine kills the seat)
    preempted_slot: int = -1
    # dispatched-but-unlanded work (speculative scheduling reads through it)
    pending_prompt: int = 0   # prefill chunk tokens in flight
    pending_first: int = 0    # 1 while the prompt-completing sample is in flight
    pending_decode: int = 0   # decode tokens in flight
    # speculative decoding accounting (engine-updated; surfaces as
    # engine.decode span attributes)
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def pending_total(self) -> int:
        return self.pending_prompt + self.pending_first + self.pending_decode

    @property
    def total_tokens(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)

    def all_tokens(self) -> List[int]:
        return self.prompt_ids + self.output_ids

    @property
    def prompt_len(self) -> int:
        return len(self.prompt_ids)

    @property
    def prefill_done(self) -> bool:
        # during decode the newest token's KV is always pending
        return self.num_computed >= self.prompt_len


@dataclass
class PrefillChunk:
    seq: SchedSeq
    start: int  # first token index in this chunk
    length: int
    # snapshot of completes_prompt at schedule time (the live property is
    # unstable once pipelined decode windows append outputs)
    final: bool = False

    @property
    def completes_prompt(self) -> bool:
        # a chunk that reaches the end of *known* tokens transitions the
        # sequence to decode (covers both fresh prompts and recompute after
        # preemption, where outputs are re-prefilled too)
        return self.start + self.length >= self.seq.total_tokens


@dataclass
class DecodeRow:
    """One decode seat in a window, snapshotted at schedule time (the seq's
    live fields may run ahead by the time the window lands)."""

    seq: SchedSeq
    base: int        # input position (num_computed seen through pendings)
    accepted: int    # tokens this window contributes (<= decode_steps)
    tok_host: int    # input token when the host knows it, else 0
    tok_src: int     # 1 = read the device ring, 0 = tok_host
    slot: int


@dataclass
class ScheduledBatch:
    prefills: List[PrefillChunk] = field(default_factory=list)
    decode_rows: List[DecodeRow] = field(default_factory=list)
    preempted: List[SchedSeq] = field(default_factory=list)
    # observability: StepRecords the engine attaches at dispatch and
    # commits at landing — riding the batch keeps attribution correct
    # with several pipelined windows in flight
    obs_records: List = field(default_factory=list)

    @property
    def decodes(self) -> List[SchedSeq]:
        # derived view — decode_rows is the single source of truth
        return [r.seq for r in self.decode_rows]

    @property
    def is_empty(self) -> bool:
        return not self.prefills and not self.decode_rows


@dataclass
class SchedulerStats:
    """ForwardPassMetrics-equivalent snapshot (ref: kv_router/protocols.rs:48)."""

    num_running: int = 0
    num_waiting: int = 0
    kv_usage: float = 0.0
    num_total_blocks: int = 0
    prefix_cache_hits: int = 0
    prefix_cache_queries: int = 0


class Scheduler:
    """Admission + step planning over the block pool."""

    def __init__(self, config: EngineConfig,
                 on_event: Optional[Callable[[KvEvent], None]] = None):
        self.config = config
        self.pool = BlockPool(config.num_blocks, on_event=on_event)
        self.waiting: Deque[SchedSeq] = deque()
        self.running: List[SchedSeq] = []
        self.stats = SchedulerStats(num_total_blocks=config.num_blocks - 1)
        # device token-ring slots (pipelined serving); slot max_num_seqs is
        # the trash slot and is never handed out
        self._free_slots: Deque[int] = deque(range(config.max_num_seqs))
        # finished seqs with windows still in flight: blocks + slot live
        # until the engine reaps them (a landed window may still scatter
        # into their blocks)
        self.zombies: List[SchedSeq] = []
        # set by the engine once it has actually built an sp prefill step —
        # config alone isn't enough (a single-device mesh can't ring), and
        # emitting a whole-prompt chunk the engine must run densely would
        # bypass max_num_batched_tokens entirely
        self.sp_enabled = False
        # speculative decoding: when set (spec_k + 1), decode windows are
        # planned this many tokens wide instead of decode_steps — the spec
        # window may land anywhere from 1 to spec_k+1 of them; the engine
        # clears it again on adaptive auto-disable
        self.spec_plan_window: Optional[int] = None
        # adaptive prefill bucket ladder (engine/ladder.py) when the
        # engine enables it: chunk caps snap DOWN to a live rung so a
        # chunked-prefill cap retired from the grid doesn't keep padding
        # chunks up to a stale bucket
        self.prefill_ladder = None
        # prefix cache manager hook: called with (queried_hashes,
        # matched_hashes) after every admission-time prefix match so the
        # radix index keeps its own hit accounting (the replay
        # prefix_vs_index cross-check compares the two)
        self.on_prefix_match: Optional[
            Callable[[List[int], List[int]], None]] = None

    # -- admission --

    def add(self, seq: SchedSeq) -> None:
        if seq.token_seq is None:  # the KVBM onboard path pre-builds it
            seq.token_seq = TokenBlockSequence.from_tokens(
                seq.prompt_ids, self.config.block_size
            )
        self.waiting.append(seq)

    def abort(self, seq: SchedSeq, reason: str = "aborted") -> None:
        if seq.status == SeqStatus.FINISHED:
            return
        self._finish(seq, reason)

    # -- planning --

    @hot_path
    def schedule(self) -> ScheduledBatch:
        batch = ScheduledBatch()
        budget = self.config.max_num_batched_tokens
        bs = self.config.block_size

        # 1. decodes: every running sequence advances up to ``decode_steps``
        # tokens per round (multi-token windows amortise the host↔device
        # roundtrip; capacity is reserved for the whole window up front).
        # Scheduling reads *through* in-flight work (pending_*): a window
        # can be planned before the previous one lands, with the input
        # token fed from the device ring (run-ahead pipelining).
        window = self.spec_plan_window or max(1, self.config.decode_steps)
        if self.config.block_lookahead:
            # SYNCHRONISED lookahead: when any running seq's runway drops
            # below half the lookahead, top up EVERY running seq to the
            # full lookahead in the same round — growth then lands in ONE
            # device-state delta (2 uploads) per cycle instead of one
            # per seq per round (the uploads are the serving bottleneck
            # on remote-PJRT, ~15 ms of serial channel time each)
            la = self.config.block_lookahead * bs
            trigger = False
            for seq in self.running:
                if seq.status is not SeqStatus.RUNNING:
                    continue
                base = (seq.num_computed + seq.pending_prompt
                        + seq.pending_decode)
                if base >= self.config.max_model_len:
                    continue
                if len(seq.block_table) * bs - base < max(window, la // 2):
                    trigger = True
                    break
            if trigger:
                for seq in self.running:
                    if seq.status is not SeqStatus.RUNNING:
                        continue
                    base = (seq.num_computed + seq.pending_prompt
                            + seq.pending_decode)
                    tgt = min(base + window - 1 + la,
                              self.config.max_model_len - 1)
                    while (len(seq.block_table) * bs <= tgt
                           and self._can_allocate(1)):
                        bid = self.pool.allocate()
                        if bid is None:
                            break
                        seq.block_table.append(bid)
        for seq in list(self.running):
            if budget <= 0:
                break
            if seq.status is not SeqStatus.RUNNING:
                continue  # preempted by an earlier seq's _ensure_slot
            base = seq.num_computed + seq.pending_prompt + seq.pending_decode
            quota = seq.max_tokens - (
                len(seq.output_ids) + seq.pending_first + seq.pending_decode
            )
            accepted = min(window, quota, self.config.max_model_len - base)
            if accepted <= 0:
                continue  # a length-finish is landing; nothing to add
            if seq.slot < 0:
                if not self._free_slots:
                    continue  # all slots zombie-held; retry after reaping
                seq.slot = self._free_slots.popleft()
            if not self._ensure_slot(seq, base + accepted - 1, batch):
                continue  # seq was preempted (or is pinned by pendings)
            tok_src = 1 if (seq.pending_first or seq.pending_decode) else 0
            tok_host = 0 if tok_src else seq.all_tokens()[base]
            batch.decode_rows.append(DecodeRow(
                seq=seq, base=base, accepted=accepted,
                tok_host=tok_host, tok_src=tok_src, slot=seq.slot,
            ))
            seq.pending_decode += accepted
            budget -= 1

        # 2. chunked prefill from the waiting queue, FIFO.  A prefill that
        # completed admission already moved into self.running, so only count
        # in-flight prefills that are NOT yet running to avoid double-counting
        def active_seqs() -> int:
            running_ids = {s.seq_id for s in self.running}
            return len(self.running) + len(
                {c.seq.seq_id for c in batch.prefills} - running_ids
            )

        while (self.waiting and budget > 0
               and active_seqs() < self.config.max_num_seqs):
            seq = self.waiting[0]
            if seq.status == SeqStatus.WAITING:
                self._match_prefix(seq)
                seq.status = SeqStatus.PREFILL
            if seq.slot < 0:
                if not self._free_slots:
                    break  # all slots zombie-held; admit after reaping
                seq.slot = self._free_slots.popleft()
            target = seq.total_tokens  # prompt (+ outputs when recomputing)
            # schedule *through* chunks still in flight (pipelined prefill)
            start = seq.num_computed + seq.pending_prompt
            remaining = target - start
            sp_thresh = self.config.sp_prefill_threshold
            sp_intent = (self.sp_enabled and sp_thresh
                         and start == 0
                         and remaining >= sp_thresh)
            if sp_intent:
                # sequence-parallel prefill: the whole fresh prompt goes as
                # one chunk (the engine shards its T axis over the mesh);
                # it may exceed the per-step token budget by design
                chunk = remaining
            else:
                # chunk ≤ budget, so a partial chunk always exhausts the
                # budget and the loop cannot schedule a token range twice.
                # Also never exceed the largest compiled prefill bucket —
                # that lets max_num_batched_tokens run past the bucket so
                # decode seats don't force prompt splits (a 512 prompt
                # split 448+64 costs a full extra dispatch + uploads).
                max_bucket = max(self.config.prefill_buckets)
                eff_cap = max_bucket
                pct = self.config.prefill_chunk_tokens
                if pct > 0:
                    # chunked prefill: slice long prompts into pct-token
                    # chunks interleaved with running decodes, instead of
                    # one whole-prompt stall. Never below a block so chunk
                    # boundaries can't strand a partial block's worth of
                    # budget forever.
                    eff_cap = min(max_bucket, max(pct, bs))
                    if self.prefill_ladder is not None:
                        # snap to the largest live rung ≤ cap: every chunk
                        # pads up to a compiled bucket, so an off-grid cap
                        # burns (bucket - cap) tokens per dispatch
                        rung = self.prefill_ladder.rung_at_most(eff_cap)
                        if rung is not None and rung >= bs:
                            eff_cap = rung
                chunk = min(budget, remaining, eff_cap)
                if (chunk < remaining and chunk < eff_cap
                        and batch.prefills):
                    # fragment caused by earlier prefills eating the
                    # budget: the tail would cost a whole extra dispatch
                    # (padded to a full bucket) — defer this prompt to
                    # the next round, which grants a fresh budget. The
                    # FIRST prefill of a batch never defers, so budget-
                    # limited chunked prefill still makes progress.
                    break
            # blocks needed to hold [start, start + chunk)
            have = len(seq.block_table)
            need = (start + chunk + bs - 1) // bs - have
            if not self._can_allocate(need):
                # shrink the chunk to what fits above the watermark
                chunk = self._max_affordable_chunk(seq, chunk, start)
                if sp_intent and chunk < remaining:
                    # can't host the full prompt → it can't ring; fall back
                    # to budgeted chunking rather than a giant dense chunk
                    chunk = min(budget, chunk)
                if chunk <= 0:
                    break  # pool exhausted; try again next step
                need = (start + chunk + bs - 1) // bs - have
            ok = True
            for _ in range(need):
                bid = self.pool.allocate()
                if bid is None:
                    ok = False
                    break
                seq.block_table.append(bid)
            if not ok:
                break
            final = start + chunk >= target
            if seq.t_scheduled is None:
                seq.t_scheduled = time.monotonic()
            batch.prefills.append(
                PrefillChunk(seq=seq, start=start, length=chunk,
                             final=final)
            )
            seq.pending_prompt += chunk
            budget -= chunk
            if final:
                seq.pending_first = 1
                self.waiting.popleft()
                self.running.append(seq)
                seq.status = SeqStatus.RUNNING

        self._refresh_stats()
        return batch

    # -- post-step bookkeeping (called by the engine executor) --

    @hot_path
    def on_prefill_executed(self, chunk: PrefillChunk,
                            sampled: Optional[int]) -> None:
        seq = chunk.seq
        seq.num_computed += chunk.length
        seq.pending_prompt = max(0, seq.pending_prompt - chunk.length)
        self._seal_complete_blocks(seq)
        if chunk.final and sampled is not None:
            seq.pending_first = 0
            self._append_token(seq, sampled)

    @hot_path
    def on_decode_executed(self, seq: SchedSeq, sampled: int) -> None:
        seq.num_computed += 1
        seq.pending_decode = max(0, seq.pending_decode - 1)
        self._seal_complete_blocks(seq)
        self._append_token(seq, sampled)

    def on_tokens_discarded(self, seq: SchedSeq, n: int,
                            first: bool = False, prompt: int = 0) -> None:
        """A landed window carried ``n`` decode tokens (plus optionally a
        prefill chunk / the prompt-completing sample) that were NOT
        applied — the seq finished or was aborted mid-flight. Clears their
        pendings and reaps the seq once nothing references its blocks/slot
        anymore."""
        if n:
            seq.pending_decode = max(0, seq.pending_decode - n)
        if prompt:
            seq.pending_prompt = max(0, seq.pending_prompt - prompt)
        if first:
            seq.pending_first = 0
        if (seq.status == SeqStatus.FINISHED and seq.pending_total == 0
                and seq in self.zombies):
            self.reap(seq)

    def reap(self, seq: SchedSeq) -> None:
        """Release a finished seq's blocks and ring slot once no in-flight
        window can touch them."""
        if seq in self.zombies:
            self.zombies.remove(seq)
        if not seq.hold_blocks:
            self._release_blocks(seq)
        self._free_slot(seq)
        self._refresh_stats()

    def finish(self, seq: SchedSeq, reason: str) -> None:
        self._finish(seq, reason)

    def check_stop(self, seq: SchedSeq) -> Optional[str]:
        if not seq.output_ids:
            return None
        last = seq.output_ids[-1]
        if last in seq.eos_token_ids:
            return "stop"
        if len(seq.output_ids) >= seq.max_tokens:
            return "length"
        if seq.total_tokens >= self.config.max_model_len:
            return "length"
        return None

    # -- internals --

    @hot_path
    def _append_token(self, seq: SchedSeq, token: int) -> None:
        seq.output_ids.append(token)
        assert seq.token_seq is not None
        seq.token_seq.append(token)

    def _seal_complete_blocks(self, seq: SchedSeq) -> None:
        """Seal blocks whose KV is fully computed AND content-complete."""
        assert seq.token_seq is not None
        bs = self.config.block_size
        computed_blocks = seq.num_computed // bs
        sealable = min(computed_blocks, len(seq.token_seq.blocks))
        for i in range(seq.num_sealed_blocks, sealable):
            tb = seq.token_seq.blocks[i]
            self.pool.seal(
                seq.block_table[i], tb.sequence_hash, tb.block_hash,
                tb.parent_sequence_hash,
            )
        seq.num_sealed_blocks = max(seq.num_sealed_blocks, sealable)

    def _match_prefix(self, seq: SchedSeq) -> None:
        """Prefix-cache lookup at admission (chained sequence hashes)."""
        if not self.config.enable_prefix_caching or seq.num_computed:
            return
        assert seq.token_seq is not None
        bs = self.config.block_size
        # leave at least one token to compute so the step produces logits
        max_match = (seq.total_tokens - 1) // bs
        matched: List[int] = []
        queried_hashes: List[int] = []
        matched_hashes: List[int] = []
        for i, tb in enumerate(seq.token_seq.blocks[:max_match]):
            self.stats.prefix_cache_queries += 1
            queried_hashes.append(tb.sequence_hash)
            bid = self.pool.lookup(tb.sequence_hash)
            if bid is None:
                break
            self.stats.prefix_cache_hits += 1
            matched.append(bid)
            matched_hashes.append(tb.sequence_hash)
        seq.block_table = matched
        seq.num_computed = len(matched) * bs
        seq.num_sealed_blocks = len(matched)
        if self.on_prefix_match is not None:
            self.on_prefix_match(queried_hashes, matched_hashes)

    def _ensure_slot(self, seq: SchedSeq, position: int,
                     batch: ScheduledBatch) -> bool:
        """Make sure a physical slot exists for ``position``; preempt the
        lowest-priority sequence (LIFO) when the pool is dry."""
        bs = self.config.block_size
        needed_blocks = position // bs + 1
        while len(seq.block_table) < needed_blocks:
            bid = self.pool.allocate()
            if bid is not None:
                seq.block_table.append(bid)
                continue
            victim = self._pick_victim(seq)
            if victim is None or victim is seq:
                if seq.pending_total > 0:
                    # in-flight windows still scatter into this seq's
                    # blocks — recompute-preemption would corrupt them.
                    # Skip this round; landing windows free capacity.
                    return False
                self._preempt(seq, batch)
                return False
            # victims always have pending_total == 0, so they can never be
            # in this batch's decode rows (rows set pending_decode at
            # planning time) — no batch cleanup needed
            self._preempt(victim, batch)
        return True

    def _pick_victim(self, requester: SchedSeq) -> Optional[SchedSeq]:
        # LIFO, but a seq with in-flight windows is unpreemptible: freeing
        # its blocks while a dispatched window scatters into them corrupts
        # whichever seq the pool hands them to next. EVACUATING seats are
        # likewise pinned: a transfer is reading their blocks.
        for cand in reversed(self.running):
            if cand is requester:
                continue
            if cand.status is not SeqStatus.RUNNING:
                continue
            if cand.pending_total == 0:
                return cand
        return None

    def preempt_recompute(self, seq: SchedSeq) -> int:
        """Preempt a quiesced seq back to the waiting queue: release its
        blocks and slot, reset computed state so admission re-prefills the
        full token history (prompt + outputs, byte-identical continuation).
        Returns the autopilot slot the seq held — the engine must mark it
        dead before the blocks recycle. Public entry for the stall
        watchdog and the HBM-pressure ladder."""
        assert seq.pending_total == 0, "preempting a seq with inflight work"
        log.info("preempting seq %s (recompute)", seq.seq_id)
        # the engine must kill the device autopilot seat before these
        # blocks recycle — preempted_slot carries the slot it held
        seq.preempted_slot = seq.slot
        slot = seq.slot
        self._release_blocks(seq)
        self._free_slot(seq)
        seq.num_computed = 0
        seq.num_sealed_blocks = 0
        seq.preemptions += 1
        seq.status = SeqStatus.WAITING
        if seq in self.running:
            self.running.remove(seq)
        # a mid-prefill seq (non-final chunk) never left the waiting deque;
        # re-adding it would double-schedule the prompt
        if seq not in self.waiting:
            self.waiting.appendleft(seq)
        return slot

    def _preempt(self, seq: SchedSeq, batch: ScheduledBatch) -> None:
        self.preempt_recompute(seq)
        batch.preempted.append(seq)

    def _release_blocks(self, seq: SchedSeq) -> None:
        for bid in seq.block_table:
            self.pool.decref(bid)
        seq.block_table = []

    def _free_slot(self, seq: SchedSeq) -> None:
        if seq.slot >= 0:
            self._free_slots.append(seq.slot)
            seq.slot = -1

    def _finish(self, seq: SchedSeq, reason: str) -> None:
        seq.status = SeqStatus.FINISHED
        seq.finish_reason = reason
        if seq in self.running:
            self.running.remove(seq)
        if seq in self.waiting:
            self.waiting.remove(seq)
        if seq.pending_total > 0:
            # in-flight windows still scatter into these blocks; the engine
            # reaps via on_tokens_discarded once they land
            if seq not in self.zombies:
                self.zombies.append(seq)
        else:
            if not seq.hold_blocks:
                self._release_blocks(seq)
            self._free_slot(seq)
        self._refresh_stats()

    def release_held(self, seq: SchedSeq) -> None:
        """Free a finished hold_blocks sequence after KV extraction."""
        self._release_blocks(seq)
        self._refresh_stats()

    # -- disagg decode-side admission (remote prefill) --

    def reserve(self, seq: SchedSeq) -> bool:
        """Pre-allocate blocks covering the prompt for KV injection
        (the decode side of disagg: the reference decode worker's engine
        pre-allocates blocks NIXL writes into, ref: disagg_serving.md
        §Efficient KV Transfer). Returns False (no side effects) when the
        pool can't cover it above the watermark."""
        seq.token_seq = TokenBlockSequence.from_tokens(
            seq.prompt_ids, self.config.block_size
        )
        bs = self.config.block_size
        need = (seq.prompt_len + bs - 1) // bs
        if not self._can_allocate(need):
            return False
        for _ in range(need):
            bid = self.pool.allocate()
            if bid is None:  # watermark said yes but pool is fragmented-dry
                self._release_blocks(seq)
                return False
            seq.block_table.append(bid)
        return True

    def admit_prefilled(self, seq: SchedSeq, first_token: int) -> None:
        """Activate a reserved sequence whose prompt KV was injected and
        whose first token was sampled remotely: seal prefix blocks (emitting
        stored events — this worker now owns those blocks) and enter the
        decode loop."""
        seq.num_computed = seq.prompt_len
        if seq.t_scheduled is None:
            # remote prefill: activation is the first scheduling event
            seq.t_scheduled = time.monotonic()
        self._seal_complete_blocks(seq)
        self._append_token(seq, first_token)
        seq.status = SeqStatus.RUNNING
        self.running.append(seq)
        self._refresh_stats()

    def _can_allocate(self, need: int) -> bool:
        watermark_blocks = self.config.watermark * (self.config.num_blocks - 1)
        return self.pool.num_free - need >= watermark_blocks

    def _max_affordable_chunk(self, seq: SchedSeq, want: int,
                              start: Optional[int] = None) -> int:
        bs = self.config.block_size
        watermark_blocks = int(
            self.config.watermark * (self.config.num_blocks - 1)
        )
        affordable = self.pool.num_free - watermark_blocks
        if affordable <= 0:
            return 0
        if start is None:
            start = seq.num_computed + seq.pending_prompt
        have_capacity = len(seq.block_table) * bs - start
        return min(want, have_capacity + affordable * bs)

    def _refresh_stats(self) -> None:
        self.stats.num_running = len(self.running)
        self.stats.num_waiting = len(self.waiting)
        self.stats.kv_usage = self.pool.usage
