"""Model + engine configuration.

``ModelConfig`` describes a Llama-class decoder-only transformer (the shapes
cover Llama 2/3 and TinyLlama-style test models). ``EngineConfig`` carries the
serving-side knobs that the reference exposes through engine flags and the
ModelRuntimeConfig (ref: lib/llm/src/local_model/runtime_config.rs:9 —
``total_kv_blocks``, ``max_num_seqs``, ``max_num_batched_tokens``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class ModelConfig:
    """Llama-class decoder-only transformer shapes."""

    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    max_position: int = 8192
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # MoE (0 experts = dense). gpt-oss-class models set these.
    num_experts: int = 0
    num_experts_per_token: int = 0
    moe_capacity_factor: float = 2.0

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    # -- canned configs ---------------------------------------------------

    @staticmethod
    def llama3_8b() -> "ModelConfig":
        return ModelConfig()

    @staticmethod
    def llama3_70b() -> "ModelConfig":
        return ModelConfig(
            hidden_size=8192, intermediate_size=28672, num_layers=80,
            num_heads=64, num_kv_heads=8,
        )

    @staticmethod
    def llama3_1b() -> "ModelConfig":
        """Llama-3.2-1B shapes — fits one small chip comfortably."""
        return ModelConfig(
            hidden_size=2048, intermediate_size=8192, num_layers=16,
            num_heads=32, num_kv_heads=8, head_dim=64,
            tie_word_embeddings=True,
        )

    @staticmethod
    def mixtral_8x7b() -> "ModelConfig":
        """Mixtral-class MoE shapes (8 experts, top-2 routing)."""
        return ModelConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8,
            rope_theta=1e6, num_experts=8, num_experts_per_token=2,
        )

    @staticmethod
    def tiny_moe(vocab_size: int = 512) -> "ModelConfig":
        """CPU-testable MoE toy (8 experts over an 8-way mesh)."""
        return ModelConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
            max_position=512, rope_theta=10000.0, dtype="float32",
            num_experts=8, num_experts_per_token=2,
        )

    @staticmethod
    def tiny(vocab_size: int = 512) -> "ModelConfig":
        """CPU-testable toy config (shapes divisible by an 8-way mesh)."""
        return ModelConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
            max_position=512, rope_theta=10000.0, dtype="float32",
        )


@dataclass(frozen=True)
class EngineConfig:
    """Serving-side engine knobs (vLLM-equivalent semantics)."""

    block_size: int = 16                # tokens per KV block
    num_blocks: int = 2048              # total KV blocks in HBM (G1 tier)
    max_num_seqs: int = 64              # max concurrently running sequences
    max_num_batched_tokens: int = 512   # per-step token budget (chunked prefill)
    watermark: float = 0.01             # min free-block fraction before admit
    max_model_len: int = 8192           # max tokens per sequence
    enable_prefix_caching: bool = True
    # decode batch sizes are padded up to the nearest bucket so XLA compiles
    # a handful of programs, not one per batch size
    decode_buckets: Tuple[int, ...] = (8, 16, 32, 64)
    # prefill chunk lengths likewise bucketed (powers of two)
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256, 512)
    # sharding: (dp, tp) or (dp, fsdp, tp) mesh axis sizes; (1, 1) =
    # single chip. Axis semantics live in parallel/layout.py (SpecLayout)
    mesh_shape: Tuple[int, ...] = (1, 1)
    # decode attention implementation: "pallas" streams KV blocks HBM→VMEM
    # with online softmax (ops/paged_attention.py); "einsum" materialises the
    # gathered context (the XLA-fusion reference path); "auto" microprobes
    # both at engine startup (engine/autotune.py)
    attention_impl: str = "pallas"
    # per-shape-class overrides for the ragged kernel ("" = inherit:
    # decode follows attention_impl, spec/prefill default to einsum).
    # attention_impl="auto" fills all three from the startup microprobe.
    attention_impl_decode: str = ""
    attention_impl_spec: str = ""
    attention_impl_prefill: str = ""
    # per-shape-class (q_tile, kv_tile) for the ragged pallas kernel.
    # (0, 0) = kernel defaults; engine/autotune.py's tile sweep fills these
    # with the fastest byte-parity-verified candidate per class (persisted
    # across runs via DYNTPU_AUTOTUNE_CACHE). q_tile must divide the class's
    # query window (decode: 1); kv_tile must divide block_size.
    attention_tile_decode: Tuple[int, int] = (0, 0)
    attention_tile_spec: Tuple[int, int] = (0, 0)
    attention_tile_prefill: Tuple[int, int] = (0, 0)
    # adaptive bucket ladders (engine/ladder.py): let the engine split hot
    # decode/prefill buckets and retire cold ones from the flight recorder's
    # live per-bucket occupancy, under ladder_compile_budget extra rungs per
    # ladder. Off by default — static buckets stay fully deterministic.
    adaptive_buckets: bool = False
    # max rungs each ladder may ADD over its lifetime; bounds steady-state
    # recompiles (one program per new rung, watchdog-attributed)
    ladder_compile_budget: int = 4
    # chunked prefill: cap each prefill chunk at this many tokens so long
    # prompts are admitted in slices interleaved with running decodes under
    # max_num_batched_tokens, instead of one whole-prompt stall that blows
    # up TTFT p99 for everyone behind it. 0 = off (chunks capped only by
    # the largest prefill bucket).
    prefill_chunk_tokens: int = 0
    # tokens generated per decode window (>1 chains steps on device via an
    # UNROLLED window fed from the device token ring, amortising the
    # host↔device roundtrip; tokens past a sequence's EOS/capacity inside
    # a window are discarded)
    decode_steps: int = 1
    # run-ahead: how many scheduled windows may be in flight before the
    # engine loop waits for a landing. >1 dispatches window N+1 (decode
    # input tokens read from the device ring) while window N's sampled
    # tokens are still being fetched — on a remote-PJRT TPU one sync is
    # ~64 ms vs a ~3 ms decode step, so the sync must never sit on the
    # dispatch path. 1 = classic synchronous loop (pp engines force 1).
    pipeline_depth: int = 2
    # decode block lookahead: best-effort extra blocks reserved past each
    # window so autopilot table/valid_until deltas (2 host uploads each)
    # amortise over lookahead*block_size tokens instead of per-block
    block_lookahead: int = 0
    # pipeline parallelism: >1 runs the unified step GPipe-style over a
    # ``pp`` mesh of that many stages (layers stage-sharded, decode
    # batches microbatched; parallel/pp_serving.py). Mutually exclusive
    # with (dp, tp) mesh_shape > (1, 1) and with decode_steps > 1.
    pp_stages: int = 1
    pp_microbatches: int = 4
    # sequence-parallel prefill: a fresh prompt at least this long is
    # prefilled as ONE chunk with its T axis sharded over all mesh devices
    # (ring attention over a flat "sp" view of the dp×tp device set), so
    # activation memory is O(T / n_devices) and BASELINE's 8k-ISL shapes
    # don't have to fit one chip's budget. 0 = disabled (chunked prefill).
    sp_prefill_threshold: int = 0
    # speculative decoding: "ngram" replaces each decode window with a
    # draft+verify window — a device-resident prompt-lookup drafter proposes
    # up to spec_k continuation tokens from the seq's own on-device token
    # history and ONE ragged [B, k+1] forward verifies them, so a single
    # host round-trip can land up to k+1 tokens. Greedy rows get exact
    # parity with spec_mode="off"; sampled rows emit 1 token per window.
    spec_mode: str = "off"              # "off" | "ngram"
    spec_k: int = 4                     # max draft tokens per window
    spec_ngram_min: int = 1             # smallest suffix n-gram to match
    spec_ngram_max: int = 3             # largest suffix n-gram to match
    # adaptive kill switch: once spec_auto_disable_window draft tokens have
    # been verified, an acceptance rate below the threshold permanently
    # falls back to plain autopilot windows (0.0 = never disable)
    spec_auto_disable_threshold: float = 0.0
    spec_auto_disable_window: int = 256
    # device token-history capacity per seat (0 = max_model_len); drafting
    # only sees the first spec_hist_cap positions of each sequence
    spec_hist_cap: int = 0
    # engine stall watchdog: a dispatched window whose results don't land
    # within stall_timeout_s + stall_timeout_per_token_s * real tokens is
    # declared wedged — the window is cancelled, its shape class
    # quarantined, and its seats recovered by recompute. 0 = watchdog off.
    stall_timeout_s: float = 0.0
    stall_timeout_per_token_s: float = 0.0
    # per-seat recompute retries after a stall before the seat errors out
    stall_seq_retries: int = 2
    # consecutive stalled windows before the worker declares itself dead
    # (aborts every seat so drain + Migration take over)
    stall_dead_threshold: int = 3
    # HBM-pressure ladder: graduated response to KV pool occupancy,
    # engaged per rung when usage crosses its threshold (0.0 = rung off).
    # rung 1: spill the coldest pending-free seat to the host pool (or
    # plain recompute without kvbm); rung 2: pause speculative windows;
    # rung 3: shed new admissions until pressure releases.
    pressure_spill_threshold: float = 0.0
    pressure_spec_threshold: float = 0.0
    pressure_shed_threshold: float = 0.0
    # hysteresis: a rung releases once usage < threshold - pressure_release
    pressure_release: float = 0.05
    # quantized serving (engine/quant.py): "bf16" keeps the model dtype
    # end to end (byte-identical to the pre-quant code path); "int8"/"fp8"
    # store weights / paged KV in 1 byte per element with per-channel
    # (weights) or per-token-per-head (KV) float32 scales riding the same
    # pytrees. Validated here so a bad dtype fails at startup, not at the
    # first dispatch.
    weight_dtype: str = "bf16"          # "bf16" | "int8" | "fp8"
    kv_dtype: str = "bf16"              # "bf16" | "int8" | "fp8"

    def __post_init__(self):
        if len(self.mesh_shape) not in (2, 3):
            raise ValueError("mesh_shape must be (dp, tp) or (dp, fsdp, tp)")
        mesh_devices = 1
        for n in self.mesh_shape:
            mesh_devices *= n
        if self.pp_stages > 1 and mesh_devices > 1:
            raise ValueError("pp_stages and a (dp, tp) mesh are exclusive")
        if self.max_num_seqs > max(self.decode_buckets):
            raise ValueError("max_num_seqs exceeds largest decode bucket")
        if self.spec_mode not in ("off", "ngram"):
            raise ValueError(f"unknown spec_mode {self.spec_mode!r}")
        if self.attention_impl not in ("pallas", "einsum", "auto"):
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}"
            )
        for cls in ("decode", "spec", "prefill"):
            v = getattr(self, f"attention_impl_{cls}")
            if v not in ("", "pallas", "einsum"):
                raise ValueError(
                    f"unknown attention_impl_{cls} {v!r}"
                )
        for cls in ("decode", "spec", "prefill"):
            tile = getattr(self, f"attention_tile_{cls}")
            if (len(tile) != 2 or tile[0] < 0 or tile[1] < 0):
                raise ValueError(
                    f"attention_tile_{cls} must be (q_tile>=0, kv_tile>=0)"
                )
            if tile[1] > 0 and self.block_size % tile[1]:
                raise ValueError(
                    f"attention_tile_{cls} kv_tile {tile[1]} must divide "
                    f"block_size {self.block_size}"
                )
        if self.attention_tile_decode[0] > 1:
            raise ValueError("decode q_tile must be 0 or 1 (one query/row)")
        if self.ladder_compile_budget < 0:
            raise ValueError("ladder_compile_budget must be >= 0")
        if self.prefill_chunk_tokens < 0:
            raise ValueError("prefill_chunk_tokens must be >= 0")
        if self.spec_mode != "off":
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if not (1 <= self.spec_ngram_min <= self.spec_ngram_max):
                raise ValueError("need 1 <= spec_ngram_min <= spec_ngram_max")
            if self.pp_stages > 1:
                raise ValueError("spec_mode requires pp_stages == 1")
        if self.stall_timeout_s < 0 or self.stall_timeout_per_token_s < 0:
            raise ValueError("stall timeouts must be >= 0")
        if self.stall_seq_retries < 0:
            raise ValueError("stall_seq_retries must be >= 0")
        if self.stall_dead_threshold < 1:
            raise ValueError("stall_dead_threshold must be >= 1")
        for rung in ("spill", "spec", "shed"):
            v = getattr(self, f"pressure_{rung}_threshold")
            if not (0.0 <= v <= 1.0):
                raise ValueError(
                    f"pressure_{rung}_threshold must be in [0, 1]"
                )
        if self.pressure_release < 0:
            raise ValueError("pressure_release must be >= 0")
        for knob in ("weight_dtype", "kv_dtype"):
            v = getattr(self, knob)
            if v not in ("bf16", "int8", "fp8"):
                raise ValueError(
                    f"unknown {knob} {v!r} (expected bf16|int8|fp8)"
                )
        if (self.weight_dtype != "bf16" or self.kv_dtype != "bf16") \
                and self.pp_stages > 1:
            raise ValueError("quantized serving requires pp_stages == 1")
        # max_num_batched_tokens MAY exceed the largest prefill bucket:
        # the scheduler caps each chunk at the bucket, so extra budget
        # just lets decode seats coexist with a full-bucket prefill

    @property
    def max_blocks_per_seq(self) -> int:
        return (self.max_model_len + self.block_size - 1) // self.block_size
