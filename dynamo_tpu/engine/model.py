"""Llama-class transformer in functional JAX with a paged KV cache.

This is the compute core the reference delegates to vLLM (ref: components/
backends/vllm/src/dynamo/vllm/main.py:97 ``setup_vllm_engine``); here it is
TPU-native. Design points:

- **One unified step function** serves both prefill chunks and decode batches:
  ``tokens [B, T]`` with per-sequence block tables. Prefill runs ``B=1`` with a
  bucketed ``T``; decode runs ``T=1`` with a bucketed ``B``. XLA compiles one
  program per (B, T, W) bucket combination.
- **Layers are unrolled** over stacked parameters (a static per-layer slice
  is a read, not a copy). The paged KV cache is per-layer arrays so each
  buffer is donated and scatter-updated IN PLACE — threading a stacked
  cache through ``lax.scan`` costs whole-cache copies every step.
- **Paged KV**: the cache is ``[L, num_blocks, KV, block_size, hd]``
  (block-major, head-contiguous); the step scatters the chunk's K/V into
  (block, offset) slots from the block table, then attends — decode via the
  Pallas paged kernel streaming blocks HBM→VMEM (ops/paged_attention.py),
  prefill via a gathered-context einsum. Physical block 0 is a trash block —
  padding positions scatter there, and the allocator never hands it out.
- **TP via shardings, not code**: parameters and cache carry
  ``jax.sharding.NamedSharding`` annotations over a ``("dp", "tp")`` mesh
  (attention/MLP column-row sharded, KV heads sharded over tp); XLA GSPMD
  inserts the all-reduces the reference gets from NCCL inside vLLM.
- **Sampling is fused** into the step (greedy / temperature / top-k / top-p,
  per-request seeds) so only
  B sampled token ids cross the host boundary per step, not ``[B, vocab]``
  logits.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..observability import compilewatch
from ..parallel import layout
from ..parallel.layout import AXIS_TP, SpecLayout, make_mesh
from . import quant
from .config import EngineConfig, ModelConfig

Params = Dict[str, Any]
Cache = Dict[str, jax.Array]


# ------------------------------ init ------------------------------------


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Random-init parameters (stacked per-layer leaves for lax.scan)."""
    dt = _dtype(cfg)
    hd = cfg.head_dim_
    D, H, KV, F, L, V = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
        cfg.intermediate_size, cfg.num_layers, cfg.vocab_size,
    )
    keys = jax.random.split(rng, 12)

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / np.sqrt(fan_in)).astype(dt)

    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": norm(keys[1], (L, D, H * hd), D),
        "wk": norm(keys[2], (L, D, KV * hd), D),
        "wv": norm(keys[3], (L, D, KV * hd), D),
        "wo": norm(keys[4], (L, H * hd, D), H * hd),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if cfg.is_moe:
        E = cfg.num_experts
        layers["w_router"] = norm(keys[9], (L, D, E), D)
        layers["w_gate"] = norm(keys[5], (L, E, D, F), D)
        layers["w_up"] = norm(keys[6], (L, E, D, F), D)
        layers["w_down"] = norm(keys[7], (L, E, F, D), F)
    else:
        layers["w_gate"] = norm(keys[5], (L, D, F), D)
        layers["w_up"] = norm(keys[6], (L, D, F), D)
        layers["w_down"] = norm(keys[7], (L, F, D), F)
    params: Params = {
        "embed": norm(keys[0], (V, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm(keys[8], (D, V), D)
    return params


def init_cache(cfg: ModelConfig, eng: EngineConfig) -> Cache:
    """Paged KV cache, block-major and head-contiguous: per-layer arrays of
    ``[num_blocks, KV, block_size, hd]`` (lists under ``"k"``/``"v"``).

    One (block, head) tile is a contiguous ``bs*hd`` run — the DMA granule
    the Pallas decode kernel streams HBM→VMEM, and the transfer unit for
    disagg/KVBM block movement. Per-layer arrays (not one stacked [L, …]
    array) are the TPU-critical choice: each layer's buffer is donated and
    scatter-updated IN PLACE. A stacked cache threaded through ``lax.scan``
    forces XLA to slice-out + update-in the whole cache every step —
    measured ~90 ms/step of pure copies on v5e for a 1B model."""
    dt = _dtype(cfg)
    shape = (eng.num_blocks, cfg.num_kv_heads, eng.block_size, cfg.head_dim_)
    if quant.is_quantized(eng.kv_dtype):
        # quantized pages (1 byte/elem) plus per-(slot, head) f32 scale
        # planes; the trash block's zero scales dequantize to exact zeros
        dt = quant.storage_dtype(eng.kv_dtype)
        sshape = shape[:-1]
        return {
            "k": [jnp.zeros(shape, dt) for _ in range(cfg.num_layers)],
            "v": [jnp.zeros(shape, dt) for _ in range(cfg.num_layers)],
            "ks": [jnp.zeros(sshape, jnp.float32)
                   for _ in range(cfg.num_layers)],
            "vs": [jnp.zeros(sshape, jnp.float32)
                   for _ in range(cfg.num_layers)],
        }
    return {
        "k": [jnp.zeros(shape, dt) for _ in range(cfg.num_layers)],
        "v": [jnp.zeros(shape, dt) for _ in range(cfg.num_layers)],
    }


# ---------------------------- shardings ----------------------------------


def param_shardings(mesh: Mesh, cfg: ModelConfig,
                    weight_dtype: str = "bf16") -> Params:
    """The canonical per-parameter table (see ``SpecLayout``): Megatron
    column/row TP over ``tp``, parameter storage over ``fsdp`` when the
    mesh carries one, vocab-sharded embed/lm_head. A quantized
    ``weight_dtype`` mirrors the ``{"q", "s"}`` leaf structure."""
    return SpecLayout.for_mesh(mesh).param_shardings(mesh, cfg,
                                                     weight_dtype)


def cache_shardings(mesh: Mesh, cfg: ModelConfig,
                    kv_dtype: str = "bf16") -> Cache:
    # KV heads sharded over tp so each shard holds the heads it computes
    return SpecLayout.for_mesh(mesh).cache_shardings(mesh, cfg, kv_dtype)


def _multi(mesh: Optional[Mesh]) -> bool:
    """Explicit in/out shardings only pay off (and only typecheck against
    axis names) on a real multi-device mesh."""
    return mesh is not None and mesh.devices.size > 1


def _io_kwargs(mesh: Optional[Mesh], cfg: ModelConfig, n_repl_in: int,
               outs: Tuple[str, ...],
               eng: Optional[EngineConfig] = None) -> Dict[str, Any]:
    """``jax.jit`` in/out sharding kwargs for a step-family function whose
    leading args are (params, cache) followed by ``n_repl_in`` replicated
    data/control args. ``outs`` names each output: "cache" (paged-cache
    layout) or "repl". Pinning both sides to the canonical layout means a
    mis-sharded arg is resharded at the boundary instead of silently
    recompiling a differently-partitioned program. ``eng`` (when given)
    carries the quantization dtypes so the scale leaves get their specs."""
    if not _multi(mesh):
        return {}
    wd = eng.weight_dtype if eng is not None else "bf16"
    kd = eng.kv_dtype if eng is not None else "bf16"
    lay = SpecLayout.for_mesh(mesh)
    repl = layout.replicated(mesh)
    pick = {"cache": lay.cache_shardings(mesh, cfg, kd), "repl": repl}
    return {
        "in_shardings": (
            lay.param_shardings(mesh, cfg, wd),
            lay.cache_shardings(mesh, cfg, kd),
        ) + (repl,) * n_repl_in,
        "out_shardings": tuple(pick[o] for o in outs),
    }


def _repl_kwargs(mesh: Optional[Mesh], n_in: int) -> Dict[str, Any]:
    """All-replicated in/out shardings (control-state updates)."""
    if not _multi(mesh):
        return {}
    repl = layout.replicated(mesh)
    return {"in_shardings": (repl,) * n_in, "out_shardings": repl}


def shard_params(params: Params, mesh: Mesh, cfg: ModelConfig,
                 weight_dtype: str = "bf16") -> Params:
    return jax.device_put(params, param_shardings(mesh, cfg, weight_dtype))


def shard_cache(cache: Cache, mesh: Mesh, cfg: ModelConfig,
                kv_dtype: str = "bf16") -> Cache:
    return jax.device_put(cache, cache_shardings(mesh, cfg, kv_dtype))


# ----------------------------- modules -----------------------------------


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * w.astype(jnp.float32)).astype(dt)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """HF-convention rotary embedding (rotate-half). x: [B, T, Hx, hd]."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    pos = jnp.maximum(positions, 0).astype(jnp.float32)  # [B, T]
    angles = pos[..., None] * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _mm(x: jax.Array, w: Any) -> jax.Array:
    """Matmul against a possibly-quantized weight leaf.

    Plain arrays take the literal ``x @ w`` — the default (bf16) path
    traces the exact pre-quant jaxpr, byte-identical outputs. Quantized
    ``{"q", "s"}`` leaves matmul the 1-byte weights (cast fuses into the
    MXU feed, so only the int8/fp8 bytes move from HBM) and apply the
    per-output-channel scale to the product — exact, because the scale is
    constant along the contraction axis."""
    if isinstance(w, dict):
        y = x @ w["q"].astype(x.dtype)
        return (y.astype(jnp.float32) * w["s"][0]).astype(x.dtype)
    return x @ w


def _layer_slice(stacked: Dict[str, Any], li: int) -> Dict[str, Any]:
    """Static per-layer slice of the stacked param tree (a read, not a
    copy); quantized ``{"q", "s"}`` leaves slice both members."""
    return {
        name: ({k: v[li] for k, v in w.items()} if isinstance(w, dict)
               else w[li])
        for name, w in stacked.items()
    }


def _dequant_leaf(w: Any, dtype) -> jax.Array:
    """Full dequantization for consumers that need a plain array (MoE
    expert dispatch)."""
    if isinstance(w, dict):
        return (w["q"].astype(jnp.float32) * w["s"]).astype(dtype)
    return w


_Q_BLOCK = 512  # query-block size for long prefill chunks: caps the f32
                # score tensor at [B, H, _Q_BLOCK, S] — an unblocked
                # 4096-token chunk against 8k context materialises 4.3 GB
                # of scores and OOMs next to a serving-sized KV cache


def _attention(
    q: jax.Array,        # [B, T, H, hd]
    k_all: jax.Array,    # [B, S, KV, hd]  gathered sequence KV
    v_all: jax.Array,    # [B, S, KV, hd]
    positions: jax.Array,  # [B, T] absolute positions (-1 = pad)
) -> jax.Array:
    T = q.shape[1]
    if T > _Q_BLOCK:
        outs = [
            _attention(q[:, t0:t0 + _Q_BLOCK], k_all, v_all,
                       positions[:, t0:t0 + _Q_BLOCK])
            for t0 in range(0, T, _Q_BLOCK)
        ]
        return jnp.concatenate(outs, axis=1)
    B, T, H, hd = q.shape
    S, KV = k_all.shape[1], k_all.shape[2]
    G = H // KV
    # bf16 inputs, f32 MXU accumulation — an .astype(f32) on the gathered
    # context would materialise it twice over in HBM
    scores = jnp.einsum(
        "btkgh,bskh->btkgs", q.reshape(B, T, KV, G, hd), k_all,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(hd)
    # causal paged mask: key slot s corresponds to absolute position s
    kpos = jnp.arange(S)[None, None, :]                  # [1, 1, S]
    valid = kpos <= positions[:, :, None]                # [B, T, S]
    scores = jnp.where(valid[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "btkgs,bskh->btkgh", probs.astype(q.dtype), v_all,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, T, H, hd).astype(q.dtype)


def attention_class(eng: EngineConfig, T: int) -> str:
    """Shape class of a ``[B, T]`` chunk: decode / spec / prefill.

    T is static at trace time, so the class (and the impl picked from it)
    is baked into each compiled step function.
    """
    if T == 1:
        return "decode"
    if eng.spec_mode != "off" and T <= eng.spec_k + 1:
        return "spec"
    return "prefill"


def resolve_attention_impl(eng: EngineConfig, attn_class: str) -> str:
    """Resolve the attention impl ("pallas" | "einsum") for a shape class.

    Per-class overrides (``attention_impl_{decode,spec,prefill}``, set
    explicitly or by the autotune probe) win; otherwise decode follows the
    legacy ``attention_impl`` knob and the T>1 classes default to einsum —
    running every CPU test's prefills through interpret-mode Pallas would
    be pointlessly slow, and on TPU the autotuner sets the fields anyway.
    """
    override = getattr(eng, f"attention_impl_{attn_class}", "")
    if override:
        return override
    if attn_class == "decode" and eng.attention_impl == "pallas":
        return "pallas"
    return "einsum"


def _class_tile(eng: EngineConfig, attn_class: str, T: int) -> Tuple[int, int]:
    """Effective ``(q_tile, kv_tile)`` for a shape class at window length T.

    Tuned tiles are advisory: a q_tile that doesn't divide this trace's T
    (a winner picked at the largest prefill bucket vs. a smaller chunk)
    falls back to the kernel default (0) instead of failing the trace.
    """
    q_tile, kv_tile = getattr(eng, f"attention_tile_{attn_class}", (0, 0))
    if q_tile > 0 and T % q_tile:
        q_tile = 0
    if kv_tile > 0 and eng.block_size % kv_tile:
        kv_tile = 0
    return q_tile, kv_tile


def _paged_decode_attention(
    eng: EngineConfig,
    mesh: Optional[Mesh],
    q: jax.Array,            # [B, 1, H, hd]
    lk: jax.Array,           # [NB, KV, bs, hd] this layer's cache (updated)
    lv: jax.Array,           # [NB, KV, bs, hd]
    block_tables: jax.Array,  # [B, W]
    seq_lens: jax.Array,      # [B] valid context incl. current token
    lks: Optional[jax.Array] = None,  # [NB, KV, bs] f32 scales (quant kv)
    lvs: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode-path attention via the Pallas paged kernel ([B, 1, H, hd]).

    When the cache is head-sharded over ``tp`` the kernel runs under
    ``shard_map`` so each shard streams only its own KV heads — a bare
    pallas_call is opaque to the GSPMD partitioner and would force an
    all-gather of the whole cache.
    """
    from ..ops.paged_attention import paged_attention_decode

    interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(
        paged_attention_decode,
        block_size=eng.block_size,
        kv_tile=_class_tile(eng, "decode", 1)[1],
        interpret=interpret,
    )
    q3 = q[:, 0]  # [B, H, hd]
    if mesh is not None and mesh.shape.get(AXIS_TP, 1) > 1:
        lay = SpecLayout.for_mesh(mesh)
        heads = layout.spec(None, lay.tp, None)
        if lks is not None:
            out = layout.shard_map(
                lambda q_, k_, v_, t_, s_, ks_, vs_: kernel(
                    q_, k_, v_, t_, s_, k_scale=ks_, v_scale=vs_
                ),
                mesh=mesh,
                in_specs=(
                    heads, lay.cache_block(), lay.cache_block(),
                    layout.spec(None, None), layout.spec(None),
                    lay.cache_scale_block(), lay.cache_scale_block(),
                ),
                out_specs=heads,
            )(q3, lk, lv, block_tables, seq_lens, lks, lvs)
        else:
            out = layout.shard_map(
                lambda q_, k_, v_, t_, s_: kernel(q_, k_, v_, t_, s_),
                mesh=mesh,
                in_specs=(
                    heads, lay.cache_block(), lay.cache_block(),
                    layout.spec(None, None), layout.spec(None),
                ),
                out_specs=heads,
            )(q3, lk, lv, block_tables, seq_lens)
    else:
        out = kernel(q3, lk, lv, block_tables, seq_lens,
                     k_scale=lks, v_scale=lvs)
    return out[:, None]


def _paged_ragged_attention(
    eng: EngineConfig,
    mesh: Optional[Mesh],
    q: jax.Array,             # [B, T, H, hd]
    lk: jax.Array,            # [NB, KV, bs, hd] this layer's cache (updated)
    lv: jax.Array,            # [NB, KV, bs, hd]
    block_tables: jax.Array,  # [B, W]
    q_len: jax.Array,         # [B] valid (prefix) queries per row, 0 = dead
    ctx_len: jax.Array,       # [B] context incl. the row's own tokens
    lks: Optional[jax.Array] = None,  # [NB, KV, bs] f32 scales (quant kv)
    lvs: Optional[jax.Array] = None,
) -> jax.Array:
    """T>1 attention (spec windows, prefill chunks) via the ragged kernel.

    Rows pack flat with stride T (``q_start = arange(B+1) * T``); the
    forward contract guarantees valid tokens are a per-row prefix, which
    is exactly the ragged layout.  Sharding story mirrors
    ``_paged_decode_attention``.
    """
    from ..ops.paged_attention import paged_attention_ragged

    B, T, H, hd = q.shape
    interpret = jax.default_backend() != "tpu"
    q_tile, kv_tile = _class_tile(eng, attention_class(eng, T), T)
    kernel = functools.partial(
        paged_attention_ragged,
        block_size=eng.block_size,
        max_q_len=T,
        q_tile=q_tile,
        kv_tile=kv_tile,
        interpret=interpret,
    )
    q_flat = q.reshape(B * T, H, hd)
    q_start = jnp.arange(B + 1, dtype=jnp.int32) * T
    if mesh is not None and mesh.shape.get(AXIS_TP, 1) > 1:
        lay = SpecLayout.for_mesh(mesh)
        heads = layout.spec(None, lay.tp, None)
        if lks is not None:
            out = layout.shard_map(
                lambda q_, k_, v_, t_, s_, ql_, cl_, ks_, vs_: kernel(
                    q_, k_, v_, t_, s_, ql_, cl_,
                    k_scale=ks_, v_scale=vs_,
                ),
                mesh=mesh,
                in_specs=(
                    heads, lay.cache_block(), lay.cache_block(),
                    layout.spec(None, None), layout.spec(None),
                    layout.spec(None), layout.spec(None),
                    lay.cache_scale_block(), lay.cache_scale_block(),
                ),
                out_specs=heads,
            )(q_flat, lk, lv, block_tables, q_start, q_len, ctx_len,
              lks, lvs)
        else:
            out = layout.shard_map(
                lambda q_, k_, v_, t_, s_, ql_, cl_: kernel(
                    q_, k_, v_, t_, s_, ql_, cl_
                ),
                mesh=mesh,
                in_specs=(
                    heads, lay.cache_block(), lay.cache_block(),
                    layout.spec(None, None), layout.spec(None),
                    layout.spec(None), layout.spec(None),
                ),
                out_specs=heads,
            )(q_flat, lk, lv, block_tables, q_start, q_len, ctx_len)
    else:
        out = kernel(q_flat, lk, lv, block_tables, q_start, q_len, ctx_len,
                     k_scale=lks, v_scale=lvs)
    return out.reshape(B, T, H, hd)


def forward(
    cfg: ModelConfig,
    eng: EngineConfig,
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B, T] int32 (0 = pad)
    positions: jax.Array,     # [B, T] int32 absolute, -1 = pad
    block_tables: jax.Array,  # [B, W] int32 physical block ids (0 = trash)
    mesh: Optional[Mesh] = None,
    ring_mesh: Optional[Mesh] = None,
    mm_embeds: Optional[jax.Array] = None,  # [B, T, D] vision embeddings
    mm_mask: Optional[jax.Array] = None,    # [B, T] True = use mm_embeds
) -> Tuple[Cache, jax.Array]:
    """Run the transformer over a token chunk, updating the paged cache.

    With ``ring_mesh`` set (an "sp" mesh over the same devices), the chunk
    MUST be a full fresh prompt (start position 0): its T axis is sharded
    over ``sp``, attention runs as an exact ppermute ring
    (parallel/ring_attention.py), and GSPMD reshards the chunk's K/V into
    the head-sharded paged cache — activations cost O(T / sp) per device.
    Pad tails are safe: ring causal masking is by absolute chunk index, so
    pad keys (index > every real query) never contaminate real rows.

    Returns (updated cache, hidden states [B, T, D]).
    """
    B, T = tokens.shape
    W = block_tables.shape[1]
    bs = eng.block_size
    hd = cfg.head_dim_
    H, KV = cfg.num_heads, cfg.num_kv_heads

    use_ring = ring_mesh is not None and T > 1
    ring_lay = SpecLayout.for_mesh(ring_mesh) if use_ring else None
    if use_ring and ring_lay.seq_axes() is None:
        use_ring = ring_lay = None  # single-device "ring" is dense attention
    # layer-boundary activation pin: ring chunks stay T-sharded over the
    # SERVING mesh's composite sequence axis, dense-path activations stay
    # replicated — one spec per boundary means GSPMD never has to guess
    # (and never falls back to involuntary rematerialization)
    lay = SpecLayout.for_mesh(mesh) if _multi(mesh) else None
    if use_ring:
        h_pin = NamedSharding(ring_mesh, ring_lay.hidden_seq())
    elif lay is not None:
        h_pin = NamedSharding(mesh, lay.hidden())
    else:
        h_pin = None

    h = jnp.take(params["embed"], tokens, axis=0)  # [B, T, D]
    if mm_embeds is not None:
        # multimodal EPD: placeholder positions take the encode worker's
        # precomputed embeddings instead of token embeddings (ref: the
        # TRT-LLM EPD flow, request_handlers/handler_base.py:64-234 — the
        # reference splices prompt embeddings the same way)
        h = jnp.where(mm_mask[..., None], mm_embeds.astype(h.dtype), h)
    if h_pin is not None:
        h = jax.lax.with_sharding_constraint(h, h_pin)

    # physical (block, offset) per (b, t); pads go to the trash block 0
    pos_safe = jnp.maximum(positions, 0)
    logical_block = pos_safe // bs                      # [B, T]
    phys_block = jnp.take_along_axis(
        block_tables, jnp.minimum(logical_block, W - 1), axis=1
    )                                                   # [B, T]
    scatter_block = jnp.where(positions >= 0, phys_block, 0).reshape(-1)
    scatter_off = jnp.where(positions >= 0, pos_safe % bs, 0).reshape(-1)

    attn_impl = resolve_attention_impl(eng, attention_class(eng, T))
    use_pallas = not use_ring and attn_impl == "pallas"
    seq_lens = q_len = ctx_len = None
    if use_pallas:
        if T == 1:
            seq_lens = jnp.maximum(positions[:, 0] + 1, 0)
        else:
            # valid tokens are a per-row prefix (the spec/prefill feed
            # contract), so count + max give the ragged-kernel metadata
            q_len = jnp.sum(positions >= 0, axis=1).astype(jnp.int32)
            ctx_len = jnp.maximum(jnp.max(positions, axis=1) + 1, 0)

    # Unrolled layer loop (NOT lax.scan): each layer's cache buffer is
    # donated and scatter-updated in place; a scanned stacked cache is
    # copied out of xs and back into ys wholesale every step (profiled at
    # ~90 ms/step of pure copies for a 1B model on v5e). Weights stay
    # stacked [L, …]; the static per-layer slice is a read, not a copy.
    kv_quant = quant.is_quantized(eng.kv_dtype)
    new_k: list = []
    new_v: list = []
    new_ks: list = []
    new_vs: list = []
    stacked = params["layers"]
    for li in range(cfg.num_layers):
        p = _layer_slice(stacked, li)
        lk, lv = cache["k"][li], cache["v"][li]   # [NB, KV, bs, hd]
        lks = cache["ks"][li] if kv_quant else None  # [NB, KV, bs] f32
        lvs = cache["vs"][li] if kv_quant else None

        x = _rms_norm(h, p["attn_norm"], cfg.rms_norm_eps)
        q = _mm(x, p["wq"]).reshape(B, T, H, hd)
        k = _mm(x, p["wk"]).reshape(B, T, KV, hd)
        v = _mm(x, p["wv"]).reshape(B, T, KV, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if use_ring:
            # projections of the T-sharded chunk stay T-sharded — without
            # the pin the column-sharded wq/wk/wv propagate a head
            # sharding into the same tensors and GSPMD remats
            qkv_pin = NamedSharding(ring_mesh, ring_lay.heads_seq())
            q = jax.lax.with_sharding_constraint(q, qkv_pin)
            k = jax.lax.with_sharding_constraint(k, qkv_pin)
            v = jax.lax.with_sharding_constraint(v, qkv_pin)

        # scatter this chunk's K/V into the paged cache
        k_upd = k.reshape(B * T, KV, hd)
        v_upd = v.reshape(B * T, KV, hd)
        if use_ring and lay is not None:
            # the one real layout change on the ring path: T-sharded K/V
            # re-lands on the cache's head sharding. GSPMD cannot
            # synthesize the seq->heads transform in one hop (it falls
            # back to involuntary full rematerialization), so stage it
            # explicitly: a planned all-gather over the sequence axes,
            # then a local slice onto the cache's tp sharding
            repl_pin = NamedSharding(mesh, layout.spec(None, None, None))
            upd_pin = NamedSharding(mesh, layout.spec(None, lay.tp, None))
            k_upd = jax.lax.with_sharding_constraint(k_upd, repl_pin)
            v_upd = jax.lax.with_sharding_constraint(v_upd, repl_pin)
            k_upd = jax.lax.with_sharding_constraint(k_upd, upd_pin)
            v_upd = jax.lax.with_sharding_constraint(v_upd, upd_pin)
        if kv_quant:
            # per-(token, head) scales: a token's stored bytes depend only
            # on its own K/V, never on block placement — so spec-decode
            # and chunked-prefill replays of the same tokens stay
            # bit-exact regardless of which block a replay scatters to
            k_upd, k_sc = quant.kv_quantize(k_upd, eng.kv_dtype)
            v_upd, v_sc = quant.kv_quantize(v_upd, eng.kv_dtype)
            lks = lks.at[scatter_block, :, scatter_off].set(k_sc)
            lvs = lvs.at[scatter_block, :, scatter_off].set(v_sc)
        lk = lk.at[scatter_block, :, scatter_off].set(k_upd)
        lv = lv.at[scatter_block, :, scatter_off].set(v_upd)

        if use_ring:
            from ..parallel.ring_attention import ring_attention

            # the ring runs over the serving mesh itself — the sequence
            # axis is the composite (dp, tp) [..fsdp] axes, so the K/V the
            # scatter reshards into the head-sharded cache never crosses a
            # mesh boundary (THE involuntary-remat source this replaces)
            seq_spec = ring_lay.heads_seq()
            attn = layout.shard_map(
                functools.partial(
                    ring_attention, axis_name=ring_lay.seq_axes()
                ),
                mesh=ring_mesh,
                in_specs=(seq_spec, seq_spec, seq_spec),
                out_specs=seq_spec,
            )(q, k, v)
        elif use_pallas and T == 1:
            attn = _paged_decode_attention(
                eng, mesh, q, lk, lv, block_tables, seq_lens,
                lks=lks, lvs=lvs,
            )
        elif use_pallas:
            attn = _paged_ragged_attention(
                eng, mesh, q, lk, lv, block_tables, q_len, ctx_len,
                lks=lks, lvs=lvs,
            )
        else:
            # gather the full context for attention: [B, W*bs, KV, hd] with
            # gathered position = w*bs + offset = absolute position
            k_all = jnp.take(
                lk, block_tables.reshape(-1), axis=0
            ).reshape(B, W, KV, bs, hd).transpose(0, 1, 3, 2, 4).reshape(
                B, W * bs, KV, hd
            )
            v_all = jnp.take(
                lv, block_tables.reshape(-1), axis=0
            ).reshape(B, W, KV, bs, hd).transpose(0, 1, 3, 2, 4).reshape(
                B, W * bs, KV, hd
            )
            if kv_quant:
                ks_all = jnp.take(
                    lks, block_tables.reshape(-1), axis=0
                ).reshape(B, W, KV, bs).transpose(0, 1, 3, 2).reshape(
                    B, W * bs, KV
                )
                vs_all = jnp.take(
                    lvs, block_tables.reshape(-1), axis=0
                ).reshape(B, W, KV, bs).transpose(0, 1, 3, 2).reshape(
                    B, W * bs, KV
                )
                k_all = quant.kv_dequantize(k_all, ks_all, q.dtype)
                v_all = quant.kv_dequantize(v_all, vs_all, q.dtype)
            attn = _attention(q, k_all, v_all, positions)
        h = h + _mm(attn.reshape(B, T, H * hd), p["wo"])

        x = _rms_norm(h, p["mlp_norm"], cfg.rms_norm_eps)
        if cfg.is_moe:
            from ..parallel.moe import moe_ffn

            D = x.shape[-1]
            out = moe_ffn(
                x.reshape(B * T, D),
                p["w_router"],
                _dequant_leaf(p["w_gate"], x.dtype),
                _dequant_leaf(p["w_up"], x.dtype),
                _dequant_leaf(p["w_down"], x.dtype),
                top_k=cfg.num_experts_per_token,
                capacity_factor=cfg.moe_capacity_factor,
            )
            h = h + out.reshape(B, T, D)
        else:
            gate = jax.nn.silu(_mm(x, p["w_gate"]).astype(jnp.float32))
            up = _mm(x, p["w_up"]).astype(jnp.float32)
            if use_ring:
                # ring chunks run the MLP sequence-parallel: activations
                # stay T-sharded, the (small) weights all-gather — pin the
                # intermediates so w_down's row sharding can't pull a
                # head-style spec onto them
                ff_pin = NamedSharding(
                    ring_mesh,
                    layout.spec(None, ring_lay.seq_axes(), None),
                )
                gate = jax.lax.with_sharding_constraint(gate, ff_pin)
                up = jax.lax.with_sharding_constraint(up, ff_pin)
            h = h + _mm((gate * up).astype(h.dtype), p["w_down"])
        if h_pin is not None:
            h = jax.lax.with_sharding_constraint(h, h_pin)
        new_k.append(lk)
        new_v.append(lv)
        if kv_quant:
            new_ks.append(lks)
            new_vs.append(lvs)

    h = _rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    out_cache: Cache = {"k": new_k, "v": new_v}
    if kv_quant:
        out_cache["ks"] = new_ks
        out_cache["vs"] = new_vs
    return out_cache, h


def logits_fn(cfg: ModelConfig, params: Params, h: jax.Array) -> jax.Array:
    head = (params["embed"].T if cfg.tie_word_embeddings
            else params["lm_head"])
    # bf16 x bf16 -> f32 on the MXU; casting the [D, V] head to f32 first
    # would materialise ~1 GB in HBM every step
    if isinstance(head, dict):
        y = jax.lax.dot_general(
            h, head["q"].astype(h.dtype),
            (((h.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return y * head["s"][0]
    return jax.lax.dot_general(
        h, head.astype(h.dtype), (((h.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# --------------------------- encode (embeddings) --------------------------


def encode_forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,      # [B, T] int32 (0 = pad)
    positions: jax.Array,   # [B, T] int32, -1 = pad
) -> jax.Array:
    """Encode-only forward: dense causal attention over the chunk, no paged
    cache — the engine step for ``/v1/embeddings`` (ref: the embeddings
    route in lib/llm/src/http/service/openai.rs:714; the reference delegates
    to an embedding engine, here the decoder itself encodes).

    Returns L2-normalised mean-pooled final hidden states ``[B, D]`` (mean
    over non-pad positions — the standard decoder-as-encoder pooling).
    """
    B, T = tokens.shape
    hd = cfg.head_dim_
    H, KV = cfg.num_heads, cfg.num_kv_heads

    h = jnp.take(params["embed"], tokens, axis=0)  # [B, T, D]
    stacked = params["layers"]
    for li in range(cfg.num_layers):
        p = _layer_slice(stacked, li)
        x = _rms_norm(h, p["attn_norm"], cfg.rms_norm_eps)
        q = _mm(x, p["wq"]).reshape(B, T, H, hd)
        k = _mm(x, p["wk"]).reshape(B, T, KV, hd)
        v = _mm(x, p["wv"]).reshape(B, T, KV, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        attn = _attention(q, k, v, positions)
        h = h + _mm(attn.reshape(B, T, H * hd), p["wo"])
        x = _rms_norm(h, p["mlp_norm"], cfg.rms_norm_eps)
        if cfg.is_moe:
            from ..parallel.moe import moe_ffn

            D = x.shape[-1]
            out = moe_ffn(
                x.reshape(B * T, D),
                p["w_router"],
                _dequant_leaf(p["w_gate"], x.dtype),
                _dequant_leaf(p["w_up"], x.dtype),
                _dequant_leaf(p["w_down"], x.dtype),
                top_k=cfg.num_experts_per_token,
                capacity_factor=cfg.moe_capacity_factor,
            )
            h = h + out.reshape(B, T, D)
        else:
            gate = jax.nn.silu(_mm(x, p["w_gate"]).astype(jnp.float32))
            up = _mm(x, p["w_up"]).astype(jnp.float32)
            h = h + _mm((gate * up).astype(h.dtype), p["w_down"])
    h = _rms_norm(h, params["final_norm"], cfg.rms_norm_eps)

    valid = (positions >= 0).astype(jnp.float32)[:, :, None]  # [B, T, 1]
    pooled = jnp.sum(h.astype(jnp.float32) * valid, axis=1) / jnp.maximum(
        jnp.sum(valid, axis=1), 1.0
    )                                                          # [B, D]
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-12)


def make_encode_fn(cfg: ModelConfig, mesh: Optional[Mesh] = None,
                   weight_dtype: str = "bf16"):
    """Jitted encode step: (params, tokens[B,T], positions[B,T]) -> [B, D].

    ``mesh`` pins params to the canonical layout (pooled embeddings are
    tiny and come back replicated)."""
    kw: Dict[str, Any] = {}
    if _multi(mesh):
        lay = SpecLayout.for_mesh(mesh)
        repl = layout.replicated(mesh)
        kw["in_shardings"] = (
            lay.param_shardings(mesh, cfg, weight_dtype), repl, repl
        )
        kw["out_shardings"] = repl
    return compilewatch.label(
        jax.jit(functools.partial(encode_forward, cfg), **kw), "encode"
    )


# ----------------------------- sampling ----------------------------------


MAX_TOP_K = 64  # top-k above this is clamped; the top-p nucleus is found
                # among these candidates (a >64-token nucleus clamps to 64)


def _row_keys(
    rng: jax.Array, seeds: jax.Array, positions: jax.Array
) -> jax.Array:
    """Per-row PRNG keys. Seeded rows (seed >= 0) get
    ``fold_in(PRNGKey(seed), position)`` — deterministic across runs,
    engine restarts, and batch composition. Unseeded rows (-1) derive from
    the engine's step rng, decorrelated per row."""

    def mk(seed, pos, i):
        seeded = jax.random.fold_in(
            jax.random.PRNGKey(jnp.maximum(seed, 0)), jnp.maximum(pos, 0)
        )
        anon = jax.random.fold_in(rng, i)
        return jnp.where(seed >= 0, seeded, anon)

    return jax.vmap(mk)(seeds, positions, jnp.arange(seeds.shape[0]))


def sample(
    logits: jax.Array,      # [B, V] float32
    rng: jax.Array,
    temperature: jax.Array,  # [B] 0.0 = greedy
    top_k: jax.Array,        # [B] 0 = disabled
    top_p: jax.Array,        # [B] <=0 or >=1 = disabled
    seeds: jax.Array,        # [B] per-request seed, -1 = engine rng
    positions: jax.Array,    # [B] absolute position being sampled
) -> jax.Array:
    """Greedy / temperature / top-k / top-p sampling, vectorised over the
    batch (ref sampling surface: lib/llm/src/protocols/common SamplingOptions
    — temperature, top_k, top_p, seed).

    The stochastic path runs under ``lax.cond`` so an all-greedy batch — the
    common serving case — pays only the argmax. Thresholds come from
    ``lax.top_k`` over MAX_TOP_K candidates, never a full V-sort; the top-p
    nucleus is therefore capped at MAX_TOP_K tokens (documented clamp, same
    spirit as the top-k cap). Seeded rows draw from their own key stream so
    (seed → output tokens) is reproducible regardless of what else is in the
    batch; sampling is gumbel-max with per-row keys.
    """
    greedy = jnp.argmax(logits, axis=-1)

    def stochastic(_):
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        scaled = logits / temp                               # [B, V]
        K = min(MAX_TOP_K, logits.shape[-1])
        k_vals, _ = jax.lax.top_k(scaled, K)                 # [B, K] desc
        # top-k threshold: the kth largest value (k clamped to K)
        safe_k = jnp.clip(top_k, 1, K)
        kth = jnp.take_along_axis(k_vals, (safe_k - 1)[:, None], axis=-1)
        thresh = jnp.where(top_k[:, None] > 0, kth, -jnp.inf)  # [B, 1]
        # top-p threshold: smallest candidate still inside the nucleus
        # (probabilities under the full softmax, candidates in desc order;
        # the first candidate is always kept)
        lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
        probs_k = jnp.exp(k_vals - lse)                      # [B, K]
        cum = jnp.cumsum(probs_k, axis=-1)
        p_on = (top_p > 0.0) & (top_p < 1.0)                 # [B]
        keep = (cum - probs_k) < jnp.where(p_on, top_p, 2.0)[:, None]
        pth = jnp.min(
            jnp.where(keep, k_vals, jnp.inf), axis=-1, keepdims=True
        )
        thresh = jnp.maximum(
            thresh, jnp.where(p_on[:, None], pth, -jnp.inf)
        )
        masked = jnp.where(scaled >= thresh, scaled, -jnp.inf)
        # gumbel-max with per-row keys (categorical would share one key
        # across the batch, breaking per-request determinism)
        keys = _row_keys(rng, seeds, positions)
        u = jax.vmap(
            lambda k: jax.random.uniform(
                k, (logits.shape[-1],),
                minval=jnp.finfo(jnp.float32).tiny, maxval=1.0,
            )
        )(keys)
        sampled = jnp.argmax(masked - jnp.log(-jnp.log(u)), axis=-1)
        return jnp.where(temperature > 0.0, sampled, greedy)

    out = jax.lax.cond(
        jnp.any(temperature > 0.0), stochastic, lambda _: greedy, None
    )
    return out.astype(jnp.int32)


# --------------------------- the step function ----------------------------


def raw_step_fn(cfg: ModelConfig, eng: EngineConfig,
                mesh: Optional[Mesh] = None,
                ring_mesh: Optional[Mesh] = None):
    """The unjitted unified prefill/decode step.

    Signature:
      step(params, cache, tokens[B,T], positions[B,T], block_tables[B,W],
           last_idx[B], rng, temperature[B], top_k[B], top_p[B], seeds[B])
        -> (cache, sampled[B])

    ``last_idx[b]`` selects which chunk position's logits to sample (the last
    valid token of the chunk).
    """

    def step(params, cache, tokens, positions, block_tables,
             last_idx, rng, temperature, top_k, top_p, seeds):
        cache, h = forward(
            cfg, eng, params, cache, tokens, positions, block_tables,
            mesh=mesh, ring_mesh=ring_mesh,
        )
        B = tokens.shape[0]
        h_last = h[jnp.arange(B), last_idx]          # [B, D]
        logits = logits_fn(cfg, params, h_last)      # [B, V]
        pos_last = jnp.take_along_axis(
            positions, last_idx[:, None], axis=1
        )[:, 0]
        sampled = sample(
            logits, rng, temperature, top_k, top_p, seeds, pos_last
        )
        return cache, sampled

    return step


def raw_multistep_fn(cfg: ModelConfig, eng: EngineConfig, K: int,
                     mesh: Optional[Mesh] = None):
    """K chained decode steps per host roundtrip.

    The serving host↔device boundary has real latency (dispatch + fetch of
    the sampled tokens); fetching once per K tokens amortises it — the
    sampled token feeds the next step entirely on device via ``lax.scan``.

    Signature:
      multistep(params, cache, tokens[B,1], positions[B,1],
                block_tables[B,W], valid_until[B], rngs[K],
                temperature[B], top_k[B], top_p[B], seeds[B])
        -> (cache, sampled[K, B])

    Rows whose position reaches ``valid_until`` (capacity / length limit)
    scatter to the trash block and their sampled tokens are garbage — the
    scheduler discards them (mid-window EOS works the same way: the extra
    tokens are computed and thrown away, which is cheaper than a mid-window
    host sync).
    """

    def multistep(params, cache, tokens, positions, block_tables,
                  valid_until, rngs, temperature, top_k, top_p, seeds):
        B = tokens.shape[0]

        def body(carry, rng_t):
            cache, tok, pos = carry
            pos_eff = jnp.where(pos < valid_until[:, None], pos, -1)
            cache, h = forward(
                cfg, eng, params, cache, tok, pos_eff, block_tables,
                mesh=mesh,
            )
            logits = logits_fn(cfg, params, h[:, 0])
            s = sample(
                logits, rng_t, temperature, top_k, top_p, seeds, pos[:, 0]
            )
            return (cache, s[:, None], pos + 1), s

        (cache, _, _), samples = jax.lax.scan(
            body, (cache, tokens, positions), rngs
        )
        return cache, samples

    return multistep


def make_step_fn(cfg: ModelConfig, eng: EngineConfig, mesh: Optional[Mesh]):
    """Jitted step with the cache donated — XLA updates it in place.

    On a multi-device mesh both sides of the jit boundary are pinned to the
    canonical ``SpecLayout``: params/cache in their table layout, data args
    replicated, the updated cache back out in the cache layout."""
    return compilewatch.label(
        jax.jit(
            raw_step_fn(cfg, eng, mesh), donate_argnums=(1,),
            **_io_kwargs(mesh, cfg, 9, ("cache", "repl"), eng=eng),
        ),
        "step",
    )


# ---------------- device-resident token ring (pipelined serving) ----------
#
# The serving hot loop must never wait on the host: on a remote-PJRT TPU
# (this environment's tunnel) ONE host sync costs ~64 ms — 20× the 1B
# model's 3 ms decode step — while enqueue-only dispatch costs ~0.3 ms.
# The fix is architectural, not a kernel: keep the autoregressive token
# feed ON DEVICE. ``last_tok`` is a small [S+1] int32 buffer indexed by a
# per-sequence slot id; every prefill/decode step writes the token it
# sampled into the sequence's slot, and decode windows READ their input
# token from it. The host then only *observes* sampled tokens (fetched
# asynchronously, one-plus windows behind) for detokenisation and stop
# checks — it is never in the dispatch critical path. Slot S is a trash
# slot (rows with slot -1 write there).
#
# Ref for the serving shape this replaces: the reference engine's
# per-step host loop (components/backends/vllm — vLLM's GPU worker reads
# sampled ids back every step; on GPU a sync is ~10 µs so it can afford
# to). TPU-first redesign: same tokens, no sync.


def raw_decode_window_fn(cfg: ModelConfig, eng: EngineConfig, K: int,
                         mesh: Optional[Mesh] = None):
    """K decode steps, UNROLLED, fed from the device token ring.

    Unrolled rather than ``lax.scan``: the paged cache must not be a scan
    carry (whole-cache copies every iteration — see ``init_cache``). K is
    static; each iteration's scatter updates the donated cache in place.

    Signature:
      window(params, cache, last_tok[S+1], tok_host[B], tok_src[B],
             slot_ids[B], positions[B,1], block_tables[B,W],
             valid_until[B], rngs[K], temperature[B], top_k[B],
             top_p[B], seeds[B])
        -> (cache, last_tok, samples[K, B])

    Input token per row: ``last_tok[slot]`` when ``tok_src > 0`` (the
    previous window / prefill wrote it there — the host may not know it
    yet), else ``tok_host`` (resumed / injected sequences). Rows whose
    position reaches ``valid_until`` scatter to the trash block; their
    garbage tokens are discarded by the scheduler. After the window, each
    row's LAST VALID sample is written back to its slot so the next window
    can chain without the host ever seeing a token.
    """

    def window(params, cache, last_tok, tok_host, tok_src, slot_ids,
               positions, block_tables, valid_until, rngs,
               temperature, top_k, top_p, seeds):
        tok = jnp.where(tok_src > 0, last_tok[slot_ids], tok_host)[:, None]
        pos = positions
        outs = []
        for k in range(K):
            pos_eff = jnp.where(pos < valid_until[:, None], pos, -1)
            cache, h = forward(
                cfg, eng, params, cache, tok, pos_eff, block_tables,
                mesh=mesh,
            )
            logits = logits_fn(cfg, params, h[:, 0])
            s = sample(
                logits, rngs[k], temperature, top_k, top_p, seeds,
                pos[:, 0],
            )
            outs.append(s)
            tok, pos = s[:, None], pos + 1
        samples = jnp.stack(outs)                            # [K, B]
        # write each row's last in-capacity sample back to its ring slot; a
        # row already at/over capacity (acc == 0 — e.g. a padding row whose
        # valid_until <= pos) produced ONLY garbage samples, so route its
        # write to the trash slot S instead of corrupting a live ring entry
        acc = jnp.clip(valid_until - positions[:, 0], 0, K)  # [B]
        final = jnp.take_along_axis(
            samples, jnp.maximum(acc - 1, 0)[None, :], axis=0
        )[0]
        S = last_tok.shape[0] - 1
        write_slots = jnp.where(acc > 0, slot_ids, S)
        last_tok = last_tok.at[write_slots].set(final)
        return cache, last_tok, samples

    return window


def make_decode_window_fn(cfg: ModelConfig, eng: EngineConfig, K: int,
                          mesh: Optional[Mesh] = None):
    """Jitted ring decode window; cache and ring buffer donated."""
    return compilewatch.label(
        jax.jit(
            raw_decode_window_fn(cfg, eng, K, mesh), donate_argnums=(1, 2),
            **_io_kwargs(mesh, cfg, 12, ("cache", "repl", "repl"), eng=eng),
        ),
        "ring_decode_window",
    )


# ------------------- decode autopilot (device-resident control) -----------
#
# The token ring removed the host from the token FEED; the autopilot
# removes it from the control feed too. On the remote-PJRT tunnel each
# host→device array upload costs ~15 ms of serial channel time — a decode
# window that uploads 11 small arrays spends 160 ms on the channel for
# 3 ms of compute (measured, 1B model). So ALL per-sequence decode state
# lives on device, indexed by slot:
#
#   ctl = {pos, valid_until, temp, top_k, top_p, seed, last_tok [S+1],
#          tables [S+1, Wcap], rng key, ctr}
#
# A steady-state decode window is dispatched with NO fresh host arrays —
# the executable reads its seats from a device-resident ``slot_rows``
# map. The host pushes packed DELTAS (one int32 [n, 6+Wcap] + one f32
# [n, 2] upload) only when membership joins/leaves, blocks grow, or a
# resumed sequence injects a host-known token, and re-uploads
# ``slot_rows`` only on membership changes. Slot S is the trash slot:
# delta pad rows target it, and dead seats (valid_until 0) advance
# nothing and scatter to the trash block.
#
# This is the TPU-first redesign of the reference's per-step engine loop
# (vLLM reads sampled ids back every step — affordable at ~10 µs GPU
# sync, fatal at 64 ms): the device runs the decode loop; the host is a
# delta stream plus a lagging observer.

CTL_I32_FIELDS = 6  # slot, pos, valid_until, top_k, seed, last_tok


def init_ctl(eng: EngineConfig, S: int, Wcap: int, seed: int = 0,
             hist_cap: int = 0):
    """Fresh device control state (host-side construction; device_put by
    the caller with a replicated sharding).

    ``hist_cap > 0`` (spec decode) adds a per-seat token history ``hist``
    [S+1, hist_cap+1] for the n-gram drafter: ``hist[s, p]`` is sequence
    s's token at position p, -1 = unknown; column hist_cap is a trash
    column for padded scatters. The autopilot window/delta fns pass the
    extra key through untouched."""
    ctl = {
        "pos": np.zeros((S + 1,), np.int32),
        "vu": np.zeros((S + 1,), np.int32),
        "temp": np.zeros((S + 1,), np.float32),
        "tk": np.zeros((S + 1,), np.int32),
        "tp": np.ones((S + 1,), np.float32),
        "seed": np.full((S + 1,), -1, np.int32),
        "last_tok": np.zeros((S + 1,), np.int32),
        "tables": np.zeros((S + 1, Wcap), np.int32),
        "key": jax.random.PRNGKey(seed),
        "ctr": np.zeros((), np.int32),
    }
    if hist_cap > 0:
        ctl["hist"] = np.full((S + 1, hist_cap + 1), -1, np.int32)
    return ctl


def raw_ctl_delta_fn(Wcap: int):
    """Apply a packed delta to the control state.

    delta_i32 [n, 6 + Wcap]: slot, pos, valid_until, top_k, seed,
    last_tok (-1 = keep the ring value — joins after an on-device prefill
    must not clobber the sampled token), then the full table row.
    delta_f32 [n, 2]: temperature, top_p. Pad rows use slot = S (trash).
    """

    def apply(ctl, delta_i32, delta_f32):
        slots = delta_i32[:, 0]
        ctl = dict(ctl)
        ctl["pos"] = ctl["pos"].at[slots].set(delta_i32[:, 1])
        ctl["vu"] = ctl["vu"].at[slots].set(delta_i32[:, 2])
        ctl["tk"] = ctl["tk"].at[slots].set(delta_i32[:, 3])
        ctl["seed"] = ctl["seed"].at[slots].set(delta_i32[:, 4])
        lt = delta_i32[:, 5]
        ctl["last_tok"] = ctl["last_tok"].at[slots].set(
            jnp.where(lt >= 0, lt, ctl["last_tok"][slots])
        )
        ctl["tables"] = ctl["tables"].at[slots].set(delta_i32[:, 6:])
        ctl["temp"] = ctl["temp"].at[slots].set(delta_f32[:, 0])
        ctl["tp"] = ctl["tp"].at[slots].set(delta_f32[:, 1])
        return ctl

    return apply


def raw_autopilot_window_fn(cfg: ModelConfig, eng: EngineConfig, K: int,
                            mesh: Optional[Mesh] = None):
    """K unrolled decode steps reading EVERYTHING from device state.

    Signature: window(params, cache, ctl, slot_rows[B]) ->
    (cache, ctl, samples[K, B]).

    Dead seats (valid_until <= pos) compute garbage into the trash block
    and advance nothing; their sample columns are discarded by the host.
    Step rngs derive from the carried key + counter, so a window dispatch
    carries zero fresh host arrays.
    """

    def window(params, cache, ctl, slot_rows):
        rows = slot_rows
        tok = ctl["last_tok"][rows][:, None]
        pos0 = ctl["pos"][rows]
        vu = ctl["vu"][rows]
        temp = ctl["temp"][rows]
        tk = ctl["tk"][rows]
        tp = ctl["tp"][rows]
        sd = ctl["seed"][rows]
        tables = ctl["tables"][rows]
        pos = pos0[:, None]
        outs = []
        for k in range(K):
            rng_k = jax.random.fold_in(ctl["key"], ctl["ctr"] * K + k)
            pos_eff = jnp.where(pos < vu[:, None], pos, -1)
            cache, h = forward(
                cfg, eng, params, cache, tok, pos_eff, tables, mesh=mesh,
            )
            logits = logits_fn(cfg, params, h[:, 0])
            s = sample(logits, rng_k, temp, tk, tp, sd, pos[:, 0])
            outs.append(s)
            tok, pos = s[:, None], pos + 1
        samples = jnp.stack(outs)                          # [K, B]
        acc = jnp.clip(vu - pos0, 0, K)                    # [B]
        final = jnp.take_along_axis(
            samples, jnp.maximum(acc - 1, 0)[None, :], axis=0
        )[0]
        S = ctl["last_tok"].shape[0] - 1
        write_rows = jnp.where(acc > 0, rows, S)
        ctl = dict(ctl)
        ctl["last_tok"] = ctl["last_tok"].at[write_rows].set(final)
        # duplicate trash rows accumulate zero (acc there is 0)
        ctl["pos"] = ctl["pos"].at[rows].add(acc)
        ctl["ctr"] = ctl["ctr"] + 1
        return cache, ctl, samples

    return window


def make_autopilot_fns(cfg: ModelConfig, eng: EngineConfig, K: int,
                       Wcap: int, mesh: Optional[Mesh] = None):
    """(window_fn, delta_fn) jitted with cache/ctl donated."""
    window = compilewatch.label(
        jax.jit(
            raw_autopilot_window_fn(cfg, eng, K, mesh), donate_argnums=(1, 2),
            **_io_kwargs(mesh, cfg, 2, ("cache", "repl", "repl"), eng=eng),
        ),
        "decode_window",
    )
    delta = compilewatch.label(
        jax.jit(
            raw_ctl_delta_fn(Wcap), donate_argnums=(0,),
            **_repl_kwargs(mesh, 3),
        ),
        "ctl_delta",
    )
    return window, delta


# ------------------- speculative decode window (draft + verify) -----------
#
# One autopilot window lands at most K tokens per host sync. The spec
# window raises the per-sync yield without a draft model: an on-device
# prompt-lookup drafter (spec/ngram.py) proposes up to k continuation
# tokens from the seat's own token history, and ONE [B, k+1] ragged
# forward verifies the chain against the paged cache — accepted prefix +
# one bonus/corrective token land per sync, up to k+1 total. Greedy rows
# are exactly parity-safe: every emitted token is the target model's own
# argmax given a correct prefix. Draft tokens that get rejected DO write
# KV at positions past the accepted point, but those positions are (a)
# never attendable by any accepted query (the causal mask is
# ``kpos <= q``), and (b) always re-scattered by the next window before
# any later query reads them — so rejected tokens never poison the cache.


def raw_spec_window_fn(cfg: ModelConfig, eng: EngineConfig, k: int,
                       ngram_min: int, ngram_max: int,
                       mesh: Optional[Mesh] = None):
    """Draft + batched-verify decode window.

    Signature: window(params, cache, ctl, slot_rows[B]) ->
    (cache, ctl, packed[k+3, B]) where packed rows 0..k are the emitted
    token candidates, row k+1 is n_emitted per seat (how many of them are
    real), and row k+2 is n_drafted (accounting).

    Drafting is restricted to greedy seats (temp <= 0); sampled seats run
    the window as a plain single-token decode step, keyed by position for
    seeded rows exactly like the non-spec path. Dead seats (vu <= pos)
    feed trash and emit 0 tokens.
    """
    from ..spec.ngram import propose_drafts

    def window(params, cache, ctl, slot_rows):
        rows = slot_rows                                   # [B]
        tok0 = ctl["last_tok"][rows]
        pos0 = ctl["pos"][rows]
        vu = ctl["vu"][rows]
        temp = ctl["temp"][rows]
        tk = ctl["tk"][rows]
        tp = ctl["tp"][rows]
        sd = ctl["seed"][rows]
        tables = ctl["tables"][rows]
        hist = ctl["hist"]                                 # [S+1, Hcap+1]
        S = ctl["last_tok"].shape[0] - 1
        Hcap = hist.shape[1] - 1
        live = vu > pos0
        # keep the history coherent with the ring: the window's input token
        # IS all_tokens[pos0] (defensive — joins already host-fill it)
        hist = hist.at[
            jnp.where(live, rows, S),
            jnp.where(live, jnp.clip(pos0, 0, Hcap - 1), Hcap),
        ].set(tok0)
        drafts = propose_drafts(hist[rows], pos0, k, ngram_min, ngram_max)
        drafts = jnp.where((temp <= 0.0)[:, None], drafts, -1)  # [B, k]
        dvalid = jnp.cumprod(
            (drafts >= 0).astype(jnp.int32), axis=1
        ).astype(bool)
        steps = jnp.arange(k + 1, dtype=jnp.int32)
        toks = jnp.concatenate(
            [tok0[:, None], jnp.where(dvalid, drafts, 0)], axis=1
        )                                                  # [B, k+1]
        pos = pos0[:, None] + steps[None, :]
        feed = jnp.concatenate(
            [jnp.ones_like(dvalid[:, :1]), dvalid], axis=1
        ) & (pos < vu[:, None])
        pos_eff = jnp.where(feed, pos, -1)
        cache, h = forward(
            cfg, eng, params, cache, toks, pos_eff, tables, mesh=mesh,
        )
        logits = logits_fn(cfg, params, h)                 # [B, k+1, V]
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]
        rng_w = jax.random.fold_in(ctl["key"], ctl["ctr"])
        s0 = sample(logits[:, 0], rng_w, temp, tk, tp, sd, pos0)
        emitted = jnp.concatenate([s0[:, None], g[:, 1:]], axis=1)
        # accept the longest draft prefix the target model reproduces; the
        # query at index i (position pos0+i) verifies draft i
        match = dvalid & (drafts == g[:, :k])              # [B, k]
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        cap = jnp.clip(vu - pos0, 0, k + 1)
        n = jnp.minimum(a + 1, cap)                        # [B] emitted
        final = jnp.take_along_axis(
            emitted, jnp.maximum(n - 1, 0)[:, None], axis=1
        )[:, 0]
        ctl = dict(ctl)
        write_rows = jnp.where(n > 0, rows, S)
        ctl["last_tok"] = ctl["last_tok"].at[write_rows].set(final)
        # duplicate trash rows accumulate zero (n there is 0)
        ctl["pos"] = ctl["pos"].at[rows].add(n)
        # append the landed tokens to the history (emitted j is
        # all_tokens[pos0+1+j]); rejects route to the trash cell
        hv = steps[None, :] < n[:, None]
        ctl["hist"] = hist.at[
            jnp.where(hv, rows[:, None], S),
            jnp.where(hv, jnp.clip(pos0[:, None] + 1 + steps[None, :],
                                   0, Hcap - 1), Hcap),
        ].set(emitted)
        ctl["ctr"] = ctl["ctr"] + 1
        ndraft = jnp.sum(dvalid.astype(jnp.int32), axis=1)
        packed = jnp.concatenate(
            [emitted.T, n[None, :], ndraft[None, :]], axis=0
        ).astype(jnp.int32)                                # [k+3, B]
        return cache, ctl, packed

    return window


def raw_spec_hist_fill_fn():
    """Host-side history injection for joining/resumed seats.

    fill(ctl, slots[n], rows[n, Hcap+1]) scatters full token-history rows
    (-1-padded) into ``ctl["hist"]``. Pad entries use slot = S (trash).
    Dispatched only on seat joins/resets — steady-state spec windows
    maintain the history on device with zero host uploads.
    """

    def fill(ctl, slots, rows):
        ctl = dict(ctl)
        ctl["hist"] = ctl["hist"].at[slots].set(rows)
        return ctl

    return fill


def make_spec_fns(cfg: ModelConfig, eng: EngineConfig, k: int,
                  ngram_min: int, ngram_max: int,
                  mesh: Optional[Mesh] = None):
    """(spec_window_fn, hist_fill_fn) jitted with cache/ctl donated."""
    window = compilewatch.label(
        jax.jit(
            raw_spec_window_fn(cfg, eng, k, ngram_min, ngram_max, mesh),
            donate_argnums=(1, 2),
            **_io_kwargs(mesh, cfg, 2, ("cache", "repl", "repl"), eng=eng),
        ),
        "spec_window",
    )
    fill = compilewatch.label(
        jax.jit(
            raw_spec_hist_fill_fn(), donate_argnums=(0,),
            **_repl_kwargs(mesh, 3),
        ),
        "spec_hist_fill",
    )
    return window, fill


def raw_ring_prefill_fn(cfg: ModelConfig, eng: EngineConfig,
                        mesh: Optional[Mesh] = None,
                        ring_mesh: Optional[Mesh] = None):
    """Unified prefill step that also posts its sampled token to the ring.

    Same compute as ``raw_step_fn`` plus:
      write_mask[B] (int32): rows completing their prompt write ``sampled``
      into ``last_tok[slot]`` so the first decode window chains on device.
    Non-completing chunks pass write_mask 0 (their sampled is discarded).

    Signature:
      prefill(params, cache, last_tok, tokens[B,T], positions[B,T],
              block_tables[B,W], last_idx[B], slot_ids[B], write_mask[B],
              rng, temperature[B], top_k[B], top_p[B], seeds[B])
        -> (cache, last_tok, sampled[B])
    """
    base = raw_step_fn(cfg, eng, mesh, ring_mesh=ring_mesh)

    def prefill(params, cache, last_tok, tokens, positions, block_tables,
                last_idx, slot_ids, write_mask, rng,
                temperature, top_k, top_p, seeds):
        cache, sampled = base(
            params, cache, tokens, positions, block_tables, last_idx,
            rng, temperature, top_k, top_p, seeds,
        )
        S = last_tok.shape[0] - 1  # trash slot
        slot_eff = jnp.where(write_mask > 0, slot_ids, S)
        last_tok = last_tok.at[slot_eff].set(sampled)
        return cache, last_tok, sampled

    return prefill


PP_SCALARS = 8   # n, start, slot, write, top_k, seed, temp_q, top_p_q
PP_QUANT = 1e4   # temperature / top_p fixed-point scale in the int pack


def raw_packed_prefill_fn(cfg: ModelConfig, eng: EngineConfig,
                          T: int, W: int,
                          mesh: Optional[Mesh] = None):
    """Ring prefill with ALL inputs packed into ONE upload.

    ``pint [1, T + W + PP_SCALARS]`` = tokens(T), tables(W), then n,
    start, slot, write, top_k, seed, temp*1e4, top_p*1e4 (fixed-point —
    1e-4 sampling-parameter resolution is far below any behavioral
    threshold). Positions are derived on device (start + iota, -1 pads),
    so one prefill costs ONE host upload instead of 8 — on remote-PJRT
    each upload is ~15 ms of serial channel time, and at ISL 512 the
    prefill upload stream was the single largest channel consumer.
    """
    base = raw_step_fn(cfg, eng, mesh)

    def prefill(params, cache, last_tok, pint, rng):
        tokens = pint[:, :T]
        tables = pint[:, T:T + W]
        n = pint[0, T + W + 0]
        start = pint[0, T + W + 1]
        slot = pint[0, T + W + 2]
        write = pint[0, T + W + 3]
        top_k = pint[0:1, T + W + 4]
        seed = pint[0:1, T + W + 5]
        temp = pint[0:1, T + W + 6].astype(jnp.float32) / PP_QUANT
        tp = pint[0:1, T + W + 7].astype(jnp.float32) / PP_QUANT
        idx = jnp.arange(T, dtype=jnp.int32)
        positions = jnp.where(idx < n, start + idx, -1)[None, :]
        last_idx = jnp.maximum(n - 1, 0)[None]
        cache, sampled = base(
            params, cache, tokens, positions, tables, last_idx, rng,
            temp, top_k, tp, seed,
        )
        S = last_tok.shape[0] - 1
        slot_eff = jnp.where(write > 0, slot, S)[None]
        last_tok = last_tok.at[slot_eff].set(sampled)
        return cache, last_tok, sampled

    return prefill


def make_packed_prefill_fn(cfg: ModelConfig, eng: EngineConfig,
                           T: int, W: int, mesh: Optional[Mesh] = None):
    return compilewatch.label(
        jax.jit(
            raw_packed_prefill_fn(cfg, eng, T, W, mesh),
            donate_argnums=(1, 2),
            **_io_kwargs(mesh, cfg, 3, ("cache", "repl", "repl"), eng=eng),
        ),
        f"packed_prefill_T{T}_W{W}",
    )


def make_ring_prefill_fn(cfg: ModelConfig, eng: EngineConfig,
                         mesh: Optional[Mesh] = None,
                         ring_mesh: Optional[Mesh] = None,
                         out_shardings=None):
    """Jitted ring prefill; cache + ring donated. ``out_shardings``
    overrides the canonical output layout if a caller needs to (the sp
    path's defaults already pin the serving cache layout)."""
    kw = _io_kwargs(mesh, cfg, 12, ("cache", "repl", "repl"), eng=eng)
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    return compilewatch.label(
        jax.jit(
            raw_ring_prefill_fn(cfg, eng, mesh, ring_mesh=ring_mesh),
            donate_argnums=(1, 2), **kw,
        ),
        "sp_ring_prefill" if ring_mesh is not None else "ring_prefill",
    )


def make_mm_prefill_fn(cfg: ModelConfig, eng: EngineConfig,
                       mesh: Optional[Mesh]):
    """Jitted multimodal prefill step: the regular unified step plus
    ``mm_embeds [B, T, D]`` / ``mm_mask [B, T]`` splicing precomputed
    vision embeddings over placeholder positions. Compiled lazily — only
    engines that actually see multimodal requests pay for it; decode
    never needs it (placeholders live in the prompt)."""

    def step(params, cache, tokens, positions, block_tables,
             last_idx, rng, temperature, top_k, top_p, seeds,
             mm_embeds, mm_mask):
        cache, h = forward(
            cfg, eng, params, cache, tokens, positions, block_tables,
            mesh=mesh, mm_embeds=mm_embeds, mm_mask=mm_mask,
        )
        B = tokens.shape[0]
        h_last = h[jnp.arange(B), last_idx]
        logits = logits_fn(cfg, params, h_last)
        pos_last = jnp.take_along_axis(
            positions, last_idx[:, None], axis=1
        )[:, 0]
        sampled = sample(
            logits, rng, temperature, top_k, top_p, seeds, pos_last
        )
        return cache, sampled

    return compilewatch.label(
        jax.jit(
            step, donate_argnums=(1,),
            **_io_kwargs(mesh, cfg, 11, ("cache", "repl"), eng=eng),
        ),
        "mm_prefill",
    )


def make_mm_ring_prefill_fn(cfg: ModelConfig, eng: EngineConfig,
                            mesh: Optional[Mesh]):
    """Ring-posting multimodal prefill (pipelined serving path): the mm
    step plus the ``last_tok`` write of ``make_ring_prefill_fn``."""

    def step(params, cache, last_tok, tokens, positions, block_tables,
             last_idx, slot_ids, write_mask, rng,
             temperature, top_k, top_p, seeds, mm_embeds, mm_mask):
        cache, h = forward(
            cfg, eng, params, cache, tokens, positions, block_tables,
            mesh=mesh, mm_embeds=mm_embeds, mm_mask=mm_mask,
        )
        B = tokens.shape[0]
        h_last = h[jnp.arange(B), last_idx]
        logits = logits_fn(cfg, params, h_last)
        pos_last = jnp.take_along_axis(
            positions, last_idx[:, None], axis=1
        )[:, 0]
        sampled = sample(
            logits, rng, temperature, top_k, top_p, seeds, pos_last
        )
        S = last_tok.shape[0] - 1
        slot_eff = jnp.where(write_mask > 0, slot_ids, S)
        last_tok = last_tok.at[slot_eff].set(sampled)
        return cache, last_tok, sampled

    return compilewatch.label(
        jax.jit(
            step, donate_argnums=(1, 2),
            **_io_kwargs(mesh, cfg, 14, ("cache", "repl", "repl"), eng=eng),
        ),
        "mm_ring_prefill",
    )


def make_sp_prefill_fn(cfg: ModelConfig, eng: EngineConfig, mesh: Mesh):
    """Jitted full-prompt sequence-parallel prefill step.

    The ring runs over the SERVING mesh itself: the chunk's T axis is
    sharded over the composite (dp, tp) [..fsdp] axes (``SpecLayout.
    seq_axes``) — NOT over a second flat ``sp`` mesh on the same devices,
    which GSPMD could only reconcile with the head-sharded cache by fully
    rematerializing every crossing tensor (the MULTICHIP_r05 storm). The
    cache's out_shardings pin the serving layout so subsequent decode
    steps see an unchanged (donated) cache. SURVEY §5 long-context;
    exact — ring attention accumulates online softmax in f32.
    """
    return compilewatch.label(
        jax.jit(
            raw_step_fn(cfg, eng, mesh, ring_mesh=mesh),
            donate_argnums=(1,),
            **_io_kwargs(mesh, cfg, 9, ("cache", "repl"), eng=eng),
        ),
        "sp_prefill",
    )


def make_sp_ring_prefill_fn(cfg: ModelConfig, eng: EngineConfig, mesh: Mesh):
    """Ring-posting variant of the sp prefill (pipelined serving path)."""
    return make_ring_prefill_fn(cfg, eng, mesh, ring_mesh=mesh)


# ------------------------ KV block transfer ops ---------------------------
#
# The disaggregated P→D data plane (role of the reference's NIXL transfer +
# block_copy.cu resharding kernels, ref: lib/llm/src/block_manager/
# distributed/transfer.rs, kernels/block_copy.cu:41): gather a sequence's
# physical blocks out of the paged cache / scatter received blocks into
# pre-allocated slots. XLA compiles these to fused gather/scatter; on TPU
# the same jitted fns ride ICI when source and destination share a mesh.


def cache_payload_keys(eng: EngineConfig) -> Tuple[str, ...]:
    """The cache dict keys a block transfer must carry: quantized caches
    add the per-(slot, head) scale planes to the K/V pages."""
    if quant.is_quantized(eng.kv_dtype):
        return ("k", "v", "ks", "vs")
    return ("k", "v")


def make_kv_ops(eng: EngineConfig, mesh: Optional[Mesh] = None):
    """(extract, inject) jitted block gather/scatter over the paged cache.

    extract(cache, block_ids[N]) -> {"k","v"}: [L, N, KV, bs, hd]
    (plus {"ks","vs"}: [L, N, KV, bs] when the cache is quantized)
    inject(cache, block_ids[N], data) -> cache  (donated, in-place scatter)

    In the block-major layout these are single-axis gathers/scatters over
    whole contiguous blocks — XLA lowers them to block-granular DMA. With
    a mesh, extract pins the transfer payload to ``SpecLayout.kv_blocks``
    (KV heads over tp — the same axis the cache shards) and inject pins
    the cache back to its serving layout, so the disagg handoff agrees
    with the cache about head placement on both ends.
    """
    keys = cache_payload_keys(eng)
    kw_ex: Dict[str, Any] = {}
    kw_in: Dict[str, Any] = {}
    if _multi(mesh):
        lay = SpecLayout.for_mesh(mesh)
        kw_ex["out_shardings"] = layout.kv_payload_shardings(mesh, keys)
        # inject returns the full per-layer cache dict: page layers pin to
        # the cache layout, scale layers to the scale layout
        page = NamedSharding(mesh, lay.cache_block())
        scale = NamedSharding(mesh, lay.cache_scale_block())
        kw_in["out_shardings"] = {
            key: (scale if key in ("ks", "vs") else page) for key in keys
        }

    def extract(cache: Cache, block_ids: jax.Array) -> Cache:
        return {
            key: jnp.stack([jnp.take(layer, block_ids, axis=0)
                            for layer in cache[key]])
            for key in keys
        }

    def inject(cache: Cache, block_ids: jax.Array, data: Cache) -> Cache:
        return {
            key: [layer.at[block_ids].set(data[key][li])
                  for li, layer in enumerate(cache[key])]
            for key in keys
        }

    return (
        # read-only gather: the serving engine keeps using the cache after
        # an extract, so donating it here would free live KV
        compilewatch.label(jax.jit(extract, **kw_ex), "kv_extract"),  # dynalint: disable=DT103
        compilewatch.label(
            jax.jit(inject, donate_argnums=(0,), **kw_in), "kv_inject"
        ),
    )
