"""The async inference engine: scheduler + jitted steps + token streaming.

Role-equivalent to vLLM's ``AsyncLLM`` in the reference's workers (ref:
components/backends/vllm/src/dynamo/vllm/main.py:97), built TPU-native: an
asyncio step loop plans batches with the continuous-batching scheduler, runs
the jitted unified prefill/decode step on device (dispatched from a dedicated
executor thread so the event loop never blocks on XLA), and streams sampled
tokens into per-request queues. KV events and ForwardPassMetrics-equivalent
stats are surfaced in-process — the seam the reference covers with ZMQ
(publisher.rs:223) collapses here because the engine is ours.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Any, AsyncIterator, Callable, Deque, Dict, List, Optional, Tuple,
)

import jax
import numpy as np

from .. import tracing
from ..observability import compilewatch
from ..observability import flops as obs_flops
from ..parallel import layout
from ..observability.flops import FlopsModel
from ..observability.stepstats import (
    DECODE, PREFILL, SPEC_VERIFY, StepRecord, StepStats,
)
from ..runtime import faults
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..utils.config import env_flag, env_float, env_str
from ..utils.hotpath import hot_path
from ..utils.logging import get_logger
from .config import EngineConfig, ModelConfig
from . import model as model_lib
from . import quant
from .scheduler import (
    KvEvent, PrefillChunk, SchedSeq, Scheduler, SchedulerStats, SeqStatus,
)

log = get_logger("engine")


@dataclass
class Request:
    """One generation request (preprocessed: token ids in)."""

    request_id: str
    token_ids: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    eos_token_ids: Tuple[int, ...] = ()
    ignore_eos: bool = False
    # multimodal EPD: precomputed vision embeddings spliced over
    # placeholder prompt positions, plus the content-addressed ids used
    # for KV block hashing (never as model inputs) so the prefix cache
    # can't serve one image's KV for another
    mm_positions: Optional[List[int]] = None
    mm_embeddings: Optional[np.ndarray] = None   # [len(mm_positions), D]
    mm_hash_token_ids: Optional[List[int]] = None


class _BatchingFetcher:
    """One thread draining a queue of (batch, handles, future), one
    ``jax.device_get`` per WINDOW, with the D2H copy started
    asynchronously at submit time. On remote-PJRT every cold get is a
    ~64 ms+ channel sync; ``copy_to_host_async`` at dispatch overlaps the
    transfer with compute, so by the time the fetch thread reaches a
    window its bytes are (usually) already host-side and the get is
    cheap. Fetching per window — instead of grouping the whole backlog
    into one get — is what keeps inter-token latency real: each window's
    tokens flush to the SSE streams as that window lands, not in one
    burst when the backlog drains."""

    def __init__(self, unpack, on_sync=None):
        import queue as _queue

        self._q: Any = _queue.Queue()
        self._unpack = unpack
        self._on_sync = on_sync   # () -> None, counts host syncs
        self._thread = None

    def ensure_started(self) -> None:
        if self._thread is None:
            import threading

            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tpu-fetch"
            )
            self._thread.start()

    def submit(self, loop, batch, handles):
        fut = loop.create_future()
        # kick off the device→host transfer now, while the next window
        # computes; the fetch thread's device_get then mostly finds the
        # bytes already resident
        for arr in self._flat(handles):
            try:
                arr.copy_to_host_async()
            except Exception:
                pass  # best-effort (some backends/arrays don't support it)
        self._q.put((loop, batch, handles, fut))
        return fut

    def stop(self) -> None:
        if self._thread is not None:
            self._q.put(None)

    @staticmethod
    def _flat(handles) -> List[Any]:
        ph, dh = handles
        return list(ph) + ([dh[0]] if dh is not None else [])

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            loop, batch, handles, fut = item
            flat = self._flat(handles)
            try:
                # THE designed host sync: one device_get per window, on the
                # fetcher thread, off the dispatch loop
                got = jax.device_get(flat) if flat else []  # dynalint: disable=DT102
                if flat and self._on_sync is not None:
                    self._on_sync()
                res, exc = self._unpack(batch, handles, got), None
            except Exception as e:  # donated-buffer poison, backend death
                res, exc = None, e
            try:
                loop.call_soon_threadsafe(_fut_set, fut, res, exc)
            except RuntimeError:
                # the loop closed under us (engine torn down mid-flight);
                # keep draining so the remaining futures get resolved
                pass


def _fut_set(fut, res, exc) -> None:
    if fut.cancelled():
        return
    if exc is not None:
        fut.set_exception(exc)
    else:
        fut.set_result(res)


@dataclass
class StepOutput:
    """One streamed generation step for a request."""

    request_id: str
    token_id: int
    index: int                 # 0-based output token index
    finished: bool = False
    finish_reason: Optional[str] = None
    num_prompt_tokens: int = 0
    cached_prompt_tokens: int = 0


def _seed31(seed) -> int:
    """Map an arbitrary user seed into the int32-safe [0, 2^31) range the
    device arrays carry (-1 = unseeded). u64-scale seeds are valid on the
    wire (ref SamplingOptions); an unmasked one would OverflowError inside
    the step loop and kill every in-flight sequence."""
    return -1 if seed is None else int(seed) & 0x7FFFFFFF


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _pow2_bucket(n: int, cap: Optional[int] = None) -> int:
    b = 1
    while b < n:
        b *= 2
    return b if cap is None else min(b, cap)


class EngineCore(AsyncEngine):
    """Device-agnostic continuous-batching engine core.

    Owns the scheduler, the asyncio step loop, per-request streaming queues,
    and KV-event/stat surfacing. Subclasses provide the actual batch
    execution: :class:`InferenceEngine` dispatches jitted JAX steps; the
    mocker (``dynamo_tpu.mocker``) simulates step timing without a device
    (ref: lib/llm/src/mocker/engine.rs:48 — same split, the reference's
    mocker also reuses the real scheduler semantics).

    ``generate`` accepts wire-format dict requests (token_ids + sampling
    options) and yields wire-format dict outputs, so it can be served directly
    by ``Endpoint.serve_endpoint``.
    """

    def __init__(self, engine_config: EngineConfig):
        self.config = engine_config
        self.scheduler = Scheduler(engine_config, on_event=self._on_kv_event)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._seqs: Dict[str, SchedSeq] = {}
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._stopped = False
        self._ids = itertools.count(1)
        self.kv_event_sink: Optional[Callable[[dict], None]] = None
        self._pending_events: List[dict] = []
        # disagg reservation epochs: seq_id -> epoch while the reservation
        # is live (reserve_sequence .. resume_prefilled/cancel_reservation).
        # Transfers stamped with an older epoch are rejected before write.
        self._kv_epoch = itertools.count(1)
        self._kv_reservations: Dict[str, int] = {}
        self.kvbm = None  # multi-tier block manager (attach_kvbm)
        self.prefix = None  # radix prefix cache (attach_prefix_cache)
        # run-ahead depth: how many scheduled windows may be in flight
        # before the loop waits for a landing. 1 = classic synchronous
        # schedule→execute→postprocess. The JAX engine raises this (device
        # dispatch is async; host syncs are ~64 ms on remote-PJRT TPUs).
        self.pipeline_depth = 1
        # counters
        self.num_generated_tokens = 0
        self.num_steps = 0
        # host syncs (device_get round-trips) — with speculative decoding
        # the headline efficiency metric is tokens landed per sync
        self.num_fetch_syncs = 0
        # SpecDecodeStats when spec decode is active (InferenceEngine sets
        # it); published worker → aggregator and stamped on decode spans
        self.spec_stats = None
        # flight recorder (observability.StepStats) when enabled;
        # InferenceEngine builds it, the mocker leaves it None
        self.obs = None
        # -- stall watchdog state (engine_config.stall_timeout_s > 0) --
        # per-seq recovery attempts; a seq over stall_seq_retries is failed
        # instead of requeued so one poisoned prompt can't loop forever
        self._stall_retries: Dict[str, int] = {}
        self._stall_streak = 0       # consecutive stalled landings
        self.num_stalls = 0
        self.stall_dead = False      # streak hit stall_dead_threshold
        # quarantined (kind, bucket) shape classes: dispatch planning routes
        # around them (next bucket up / einsum impl) after a stall
        self._shape_quarantine: set = set()
        self._window_seq = itertools.count(1)  # fault key for engine.stall
        # -- HBM-pressure ladder state (pressure_*_threshold > 0) --
        self.pressure_level = 0          # 0 idle .. 3 shedding
        self.pressure_shedding = False   # rung 3: submit() rejects
        self._pressure_spec_paused = False  # rung 2: spec decode paused
        self._pressure_spec_saved = None    # spec_plan_window to restore
        self._pressure_spill_cool = 0    # min ticks between rung-1 spills
        self.num_pressure_spills = 0
        self.num_pressure_shed = 0
        self.pressure_peak = 0       # highest rung reached this lifetime

    # ------------------------- lifecycle -------------------------------

    async def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.create_task(self._run_loop())

    async def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        # fail everything still queued/running so no submit() consumer hangs
        for seq in list(self._seqs.values()):
            if seq.status != SeqStatus.FINISHED:
                self.scheduler.abort(seq, "shutdown")
                self._emit_finish(seq, "shutdown")
        self._shutdown_executor()

    def _shutdown_executor(self) -> None:
        pass

    @property
    def stats(self) -> SchedulerStats:
        return self.scheduler.stats

    def clear_kv_blocks(self) -> None:
        """Drop the prefix cache (ref: http clear_kv_blocks endpoint)."""
        self.scheduler.pool.clear()

    def attach_prefix_cache(self, config=None, worker_id: int = 0,
                            plane=None):
        """Enable the radix-tree prefix index on this engine. Works with
        or without a KVBM (index-only mode still gives the scheduler-hit
        cross-check accounting); attach AFTER ``attach_kvbm`` so tier
        transitions (offload/G4/drop) are hooked too."""
        from ..prefix.manager import PrefixCacheManager

        self.prefix = PrefixCacheManager(
            self, kvbm=self.kvbm, config=config, worker_id=worker_id,
            plane=plane,
        )
        self.scheduler.on_prefix_match = self.prefix.on_scheduler_match
        return self.prefix

    # ------------------------- submission ------------------------------

    async def submit(self, request: Request) -> AsyncIterator[StepOutput]:
        """Submit a request; yields StepOutputs as tokens are generated."""
        await self.start()
        if self.pressure_shedding:
            # the loop only ticks the ladder while seats are live; if the
            # pool drained since the last pass, re-evaluate here so an idle
            # engine doesn't shed forever on a stale flag
            self._pressure_tick()
        if self.pressure_shedding:
            # rung 3 of the HBM-pressure ladder: refuse new admissions
            # while resident seats drain; the router retries elsewhere
            self.num_pressure_shed += 1
            raise RuntimeError(
                "admission shed: HBM pressure over pressure_shed_threshold"
            )
        if self.stall_dead:
            raise RuntimeError(
                "engine declared dead after repeated dispatch stalls"
            )
        if not request.token_ids:
            raise ValueError("empty prompt")
        if len(request.token_ids) >= self.config.max_model_len:
            raise ValueError(
                f"prompt length {len(request.token_ids)} exceeds "
                f"max_model_len {self.config.max_model_len}"
            )
        if request.mm_positions:
            # admission-time rejection fails only THIS request; a raise in
            # the step would abort every co-scheduled request (and, for
            # multi-host, after parts of the batch reached followers)
            if getattr(self, "step_sink", None) is not None:
                raise ValueError(
                    "multimodal prefill is not supported in multi-host "
                    "step-replication mode"
                )
            if getattr(self, "pp", 0) > 1:
                raise ValueError(
                    "multimodal prefill unsupported on a pipeline-parallel "
                    "engine"
                )
            model_cfg = getattr(self, "model_config", None)
            if (model_cfg is not None and request.mm_embeddings is not None
                    and np.asarray(request.mm_embeddings).shape[-1]
                    != model_cfg.hidden_size):
                raise ValueError(
                    f"mm embedding width "
                    f"{np.asarray(request.mm_embeddings).shape[-1]} != "
                    f"model hidden size {model_cfg.hidden_size} — is the "
                    f"encode worker's --model-dim wrong?"
                )
        seq = SchedSeq(
            seq_id=request.request_id or f"seq-{next(self._ids)}",
            prompt_ids=list(request.token_ids),
            max_tokens=max(1, request.max_tokens),
            eos_token_ids=(frozenset() if request.ignore_eos
                           else frozenset(request.eos_token_ids)),
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            seed=_seed31(request.seed),
            mm_positions=(list(request.mm_positions)
                          if request.mm_positions else None),
            mm_embeddings=request.mm_embeddings,
        )
        if request.mm_positions:
            # content-addressed KV hashing: block hashes chain over ids
            # that fold in the image content, so the prefix cache can't
            # serve one image's KV for a prompt carrying another
            from ..tokens import TokenBlockSequence

            hash_ids = request.mm_hash_token_ids
            if hash_ids is None or len(hash_ids) != len(request.token_ids):
                raise ValueError(
                    "multimodal requests need mm_hash_token_ids aligned "
                    "with token_ids"
                )
            if (request.mm_embeddings is None
                    or len(request.mm_embeddings)
                    != len(request.mm_positions)):
                raise ValueError(
                    "mm_embeddings rows must match mm_positions"
                )
            seq.token_seq = TokenBlockSequence.from_tokens(
                list(hash_ids), self.config.block_size
            )
        if self.kvbm is not None or self.prefix is not None:
            # promote host-tier prefix blocks into G1 before admission so
            # the scheduler's prefix match serves them as native hits;
            # the token sequence is built once here and reused by the
            # scheduler (hash-chaining the prompt is O(prompt_len))
            from ..tokens import TokenBlockSequence

            if seq.token_seq is None:  # mm requests pre-built theirs
                seq.token_seq = TokenBlockSequence.from_tokens(
                    seq.prompt_ids, self.config.block_size
                )
            try:
                if self.prefix is not None:
                    # peer-G1 device-plane pull, then the KVBM tier chain
                    await self.prefix.onboard(seq.token_seq)
                else:
                    await self.kvbm.onboard_prefix(seq.token_seq)
            except Exception:
                log.exception("kvbm onboard failed — prefilling from scratch")
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[seq.seq_id] = queue
        self._seqs[seq.seq_id] = seq
        self.scheduler.add(seq)
        self._wake.set()
        try:
            while True:
                out = await queue.get()
                yield out
                if out.finished:
                    return
        finally:
            self._drop(seq)

    def _ap_mark_dead(self, slot: int) -> None:
        """Autopilot hook (overridden by the JAX engine): a seat whose seq
        finished must be killed on device before its blocks recycle."""

    def abort(self, seq_id: str, reason: str = "cancelled") -> None:
        seq = self._seqs.get(seq_id)
        if seq is not None and seq.status != SeqStatus.FINISHED:
            self._ap_mark_dead(seq.slot)
            self.scheduler.abort(seq, reason)
            self._emit_finish(seq, reason)

    # --------------- disaggregated prefill/decode hooks ----------------
    # (ref: the decode/prefill handler split in components/backends/vllm/
    #  src/dynamo/vllm/handlers.py:89,207 — here the engine itself exposes
    #  the hold/reserve/resume seams the reference gets from vLLM's
    #  kv_transfer connector)

    async def prefill_held(self, request: Request):
        """Prefill-worker side: run the prompt to its first token, keeping
        the KV blocks alive for extraction. Returns (seq, first_token);
        caller must ``release_held(seq)`` after extracting."""
        await self.start()
        if not request.token_ids:
            raise ValueError("empty prompt")
        seq = SchedSeq(
            seq_id=request.request_id or f"seq-{next(self._ids)}",
            prompt_ids=list(request.token_ids),
            max_tokens=1,
            eos_token_ids=frozenset(),
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            seed=_seed31(request.seed),
            hold_blocks=True,
        )
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[seq.seq_id] = queue
        self._seqs[seq.seq_id] = seq
        self.scheduler.add(seq)
        self._wake.set()
        try:
            out = await queue.get()
        except asyncio.CancelledError:
            # Hard-cancelled mid-prefill (queue worker killed, caller
            # torn down): the held handle never reaches the caller, so
            # nobody can release_held — drop the hold ourselves. With
            # hold_blocks cleared, _finish/reap free the blocks the
            # moment no in-flight window can still scatter into them.
            seq.hold_blocks = False
            if seq.status == SeqStatus.FINISHED:
                if seq.pending_total == 0 and seq not in self.scheduler.zombies:
                    self.scheduler.release_held(seq)
            else:
                self.abort(seq.seq_id, "cancelled")
            self._queues.pop(seq.seq_id, None)
            self._seqs.pop(seq.seq_id, None)
            raise
        if out.finish_reason not in ("length", "stop"):
            self.release_held(seq)
            raise RuntimeError(
                f"remote prefill failed: {out.finish_reason}"
            )
        return seq, out.token_id

    def release_held(self, seq: SchedSeq) -> None:
        self.scheduler.release_held(seq)
        self._queues.pop(seq.seq_id, None)
        self._seqs.pop(seq.seq_id, None)

    def reserve_sequence(self, request: Request) -> Optional[SchedSeq]:
        """Decode-worker side: pre-allocate prompt blocks for KV injection.
        Returns None when the pool can't host the prompt right now (caller
        falls back to local prefill)."""
        seq = SchedSeq(
            seq_id=request.request_id or f"seq-{next(self._ids)}",
            prompt_ids=list(request.token_ids),
            max_tokens=max(1, request.max_tokens),
            eos_token_ids=(frozenset() if request.ignore_eos
                           else frozenset(request.eos_token_ids)),
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            seed=_seed31(request.seed),
        )
        if not self.scheduler.reserve(seq):
            return None
        # epoch-guard the reservation: any transfer targeting these blocks
        # must present this epoch, so a delayed write aimed at a recycled
        # reservation (same seq id, new blocks) is rejected, not scattered
        seq.kv_epoch = next(self._kv_epoch)
        self._kv_reservations[seq.seq_id] = seq.kv_epoch
        self._queues[seq.seq_id] = asyncio.Queue()
        self._seqs[seq.seq_id] = seq
        return seq

    def cancel_reservation(self, seq: SchedSeq) -> None:
        self._kv_reservations.pop(seq.seq_id, None)
        self.scheduler.release_held(seq)  # reserved blocks, same release
        self._queues.pop(seq.seq_id, None)
        self._seqs.pop(seq.seq_id, None)

    def reservation_valid(self, seq_id: str, epoch: int) -> bool:
        """True while ``seq_id``'s reservation is live *and* carries
        ``epoch``. Both the device-plane scatter and the wire-relay inject
        check this immediately before writing; it also tells the orphan
        sweeper a reservation is still safe to cancel."""
        return self._kv_reservations.get(seq_id) == epoch

    async def resume_prefilled(
        self, seq: SchedSeq, first_token: int
    ) -> AsyncIterator[StepOutput]:
        """Decode-worker side: activate a reserved sequence whose KV was
        injected; streams from the remotely-sampled first token onward."""
        await self.start()
        # the reservation window closes here: late transfers must not write
        # into a sequence that is actively decoding
        self._kv_reservations.pop(seq.seq_id, None)
        self.scheduler.admit_prefilled(seq, first_token)
        self._emit_token(seq)
        self._wake.set()
        queue = self._queues[seq.seq_id]
        try:
            while True:
                out = await queue.get()
                yield out
                if out.finished:
                    return
        finally:
            self._drop(seq)

    def _drop(self, seq: SchedSeq) -> None:
        if seq.status != SeqStatus.FINISHED:
            self.scheduler.abort(seq, "cancelled")
        self._queues.pop(seq.seq_id, None)
        self._seqs.pop(seq.seq_id, None)

    # --------------------- AsyncEngine (wire) --------------------------

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        """Wire-format adapter: dict in, dict stream out."""
        mm = request.get("mm") or {}
        mm_embeddings = None
        if mm:
            from ..multimodal.encoder import array_from_wire

            mm_embeddings = array_from_wire(mm["embeddings"])
        req = Request(
            request_id=context.id,
            token_ids=list(request["token_ids"]),
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            seed=request.get("seed"),
            eos_token_ids=tuple(request.get("eos_token_ids", ())),
            ignore_eos=bool(request.get("ignore_eos", False)),
            mm_positions=(list(mm["positions"]) if mm else None),
            mm_embeddings=mm_embeddings,
            mm_hash_token_ids=(list(mm["hash_token_ids"]) if mm else None),
        )
        async def _on_stop() -> None:
            await context.wait_stopped()
            self.abort(req.request_id,
                       "killed" if context.is_killed() else "cancelled")

        watcher = asyncio.create_task(_on_stop())
        t_submit = time.monotonic()
        seq_ref: Optional[SchedSeq] = None
        try:
            async for out in self.submit(req):
                if seq_ref is None:
                    # grab the scheduler-side state before _drop can pop it;
                    # its t_scheduled/t_first_token stamps feed the spans
                    seq_ref = self._seqs.get(req.request_id)
                if context.is_killed():
                    return
                yield {
                    "token_ids": [out.token_id],
                    "index": out.index,
                    "finished": out.finished,
                    "finish_reason": out.finish_reason,
                    "num_prompt_tokens": out.num_prompt_tokens,
                }
                if out.finished:
                    return
        finally:
            watcher.cancel()
            self._record_stage_spans(context, t_submit, seq_ref)

    def _record_stage_spans(
        self, context: Context, t_submit: float, seq: Optional[SchedSeq]
    ) -> None:
        """Attribute engine time to worker.queue / engine.prefill /
        engine.decode spans from the scheduler's monotonic stamps. Recorded
        after the fact (no live span objects in the step loop) so the
        per-token hot path carries zero tracing overhead."""
        tracer = tracing.get_tracer()
        end = time.monotonic()
        t_sched = seq.t_scheduled if seq is not None else None
        t_first = seq.t_first_token if seq is not None else None
        tracer.record("worker.queue", context,
                      start_mono=t_submit, end_mono=(t_sched or end))
        if t_sched is not None:
            tracer.record("engine.prefill", context,
                          start_mono=t_sched, end_mono=(t_first or end))
        if t_first is not None:
            attrs = {"num_tokens": len(seq.output_ids)}
            if getattr(self, "spec_stats", None) is not None:
                attrs["spec_drafted"] = seq.spec_drafted
                attrs["spec_accepted"] = seq.spec_accepted
            if self.obs is not None:
                osnap = self.obs.snapshot()
                attrs["mfu"] = round(osnap["mfu"], 6)
                attrs["goodput_tok_s"] = round(osnap["goodput_tok_s"], 3)
                attrs["padding_waste_ratio"] = round(
                    osnap["padding_waste_ratio"], 6
                )
            tracer.record("engine.decode", context, start_mono=t_first,
                          end_mono=end, attrs=attrs)

    # --------------------- flight recorder surface ---------------------

    def obs_snapshot(self) -> dict:
        """One merged dict of live recorder gauges (stepstats window) and
        compile-watchdog counters; {} when the recorder is disabled."""
        if self.obs is None:
            return {}
        from ..observability import compilewatch
        snap = self.obs.snapshot()
        snap.update(compilewatch.snapshot())
        snap["stalls_total"] = self.num_stalls
        snap["stall_dead"] = int(self.stall_dead)
        snap["stall_quarantined_shapes"] = len(self._shape_quarantine)
        snap["pressure_level"] = self.pressure_level
        snap["pressure_peak"] = self.pressure_peak
        snap["pressure_spills_total"] = self.num_pressure_spills
        snap["pressure_shed_total"] = self.num_pressure_shed
        # adaptive bucket ladders (InferenceEngine only): scalar gauges by
        # the exact keys observability.gauges reads; the rungs tuple is
        # non-scalar and stays off the wire dict
        for kind, lad in getattr(self, "_ladders", {}).items():
            ls = lad.snapshot()
            snap[f"ladder_{kind}_rungs"] = ls["rungs"]
            snap[f"ladder_{kind}_rungs_n"] = len(ls["rungs"])
            snap[f"ladder_{kind}_splits_total"] = ls["splits_total"]
            snap[f"ladder_{kind}_retires_total"] = ls["retires_total"]
            snap[f"ladder_{kind}_budget_remaining"] = ls["budget_remaining"]
            snap[f"ladder_{kind}_converged"] = int(ls["converged"])
        return snap

    def mark_obs_warmup_done(self) -> None:
        """Drop warmup steps from the window and arm the steady-state
        recompile watchdog. Call after warmup traffic has drained."""
        if self.obs is None:
            return
        from ..observability import compilewatch
        self.obs.mark_warmup_done()
        compilewatch.mark_warmup_done()

    # ------------------------- step loop -------------------------------

    async def _execute_batch_async(self, batch) -> Tuple[List[int], List[int]]:
        """Execute one scheduled batch; returns (prefill, decode) samples."""
        raise NotImplementedError

    async def _run_loop(self) -> None:
        if self.pipeline_depth > 1:
            await self._run_loop_pipelined()
        else:
            await self._run_loop_sync()

    async def _run_loop_pipelined(self) -> None:
        """Run-ahead loop: schedule and dispatch window N+1 while window N
        is still computing/fetching. Decode input tokens ride the device
        token ring, so no dispatch ever waits on a host fetch; sampled
        tokens are observed one-plus windows behind for emission and stop
        checks. Landings are applied strictly in dispatch order."""
        inflight: Deque[Tuple[Any, Any]] = deque()

        async def land_next() -> None:
            batch0, fut = inflight.popleft()
            try:
                results = await asyncio.wait_for(
                    self._landing(batch0, fut), self._stall_deadline(batch0)
                )
            except asyncio.TimeoutError:
                # the head landing blew its deadline: every younger window
                # reads the wedged window's ring state, so the whole
                # run-ahead pipeline is cancelled and recovered together
                wedged = [batch0]
                self._swallow_future(fut)
                while inflight:
                    b, f = inflight.popleft()
                    wedged.append(b)
                    self._swallow_future(f)
                self._on_stall(wedged)
                return
            except Exception:
                log.exception("window failed; aborting its seqs")
                self._abort_batch(batch0)
                return
            self._stall_streak = 0
            try:
                self._postprocess(batch0, results)
            except Exception:
                log.exception("postprocess failed")
            self._flush_kv_events()

        while not self._stopped:
            while inflight and inflight[0][1].done():
                await land_next()
            self._pressure_tick()
            batch = self.scheduler.schedule()
            self._mark_preempted_seats(batch)
            if batch.is_empty:
                if inflight:
                    await land_next()
                    continue
                if self.scheduler.waiting and not self.scheduler.running:
                    seq = self.scheduler.waiting[0]
                    log.error("seq %s cannot fit in KV pool — failing",
                              seq.seq_id)
                    self.scheduler.abort(seq, "error")
                    self._emit_finish(seq, "error")
                    continue
                self._wake.clear()
                if self.kvbm is not None:
                    try:
                        while (not self._wake.is_set()
                               and await self.kvbm.tick()):
                            pass
                    except Exception:
                        log.exception("kvbm idle drain failed")
                if self._stopped:
                    break
                await self._wake.wait()
                continue
            self._arm_stall_fault(batch)
            try:
                fut = await self._dispatch_batch_async(batch)
            except Exception:
                log.exception("dispatch failed; aborting scheduled seqs")
                self._abort_batch(batch)
                continue
            inflight.append((batch, fut))
            while len(inflight) >= self.pipeline_depth:
                await land_next()
            if self.kvbm is not None:
                try:
                    await self.kvbm.tick()
                except Exception:
                    log.exception("kvbm offload tick failed")
        while inflight:  # drain so stop() leaves consistent bookkeeping
            await land_next()

    def _abort_batch(self, batch) -> None:
        """Fail every seq a dispatched-or-dispatching batch touches and
        clear the speculative pendings it registered. Seats are marked
        dead BEFORE the abort releases blocks — otherwise the device
        autopilot keeps scattering into recycled blocks."""
        for chunk in batch.prefills:
            seq = chunk.seq
            self.scheduler.on_tokens_discarded(
                seq, 0, first=chunk.final, prompt=chunk.length
            )
            if seq.status != SeqStatus.FINISHED:
                self._ap_mark_dead(seq.slot)
                self.scheduler.abort(seq, "error")
                self._emit_finish(seq, "error")
        for row in batch.decode_rows:
            seq = row.seq
            self.scheduler.on_tokens_discarded(seq, row.accepted)
            if seq.status != SeqStatus.FINISHED:
                self._ap_mark_dead(row.slot)
                self.scheduler.abort(seq, "error")
                self._emit_finish(seq, "error")

    async def _dispatch_batch_async(self, batch):
        """Enqueue the batch's device work; resolve to a future of fetched
        results. Overridden by the JAX engine; the base class executes
        synchronously (mocker paths keep pipeline_depth 1)."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        try:
            fut.set_result(await self._execute_batch_async(batch))
        except Exception as e:  # pragma: no cover
            fut.set_exception(e)
        return fut

    def _mark_preempted_seats(self, batch) -> None:
        """A preempted seq's blocks were just released — its device seat
        must die before they recycle, even if this batch is otherwise
        empty (the kill rides the next dispatch, which in-order precedes
        any reuse)."""
        for seq in batch.preempted:
            if seq.preempted_slot >= 0:
                self._ap_mark_dead(seq.preempted_slot)
                seq.preempted_slot = -1

    # ----------------------- stall watchdog ----------------------------
    # A wedged device dispatch (deadlocked collective, runaway recompile,
    # driver hang) would otherwise freeze the loop forever: every queued
    # request hangs and the worker looks alive to the router. The watchdog
    # bounds each landing by a deadline scaled to the window's token count,
    # cancels the wedged window, quarantines the shape class that wedged,
    # and replays the touched seats from their journal (prompt + emitted
    # tokens) — bounded retries per seat, bounded streak per worker.

    def _stall_deadline(self, batch) -> Optional[float]:
        """Deadline for one landing; None disables (stall_timeout_s <= 0).
        Scales with scheduled work so big prefill windows aren't false
        positives at the same setting that catches a wedged decode."""
        base = self.config.stall_timeout_s
        if base <= 0:
            return None
        n = sum(c.length for c in batch.prefills)
        n += sum(r.accepted for r in batch.decode_rows)
        return base + self.config.stall_timeout_per_token_s * n

    def _arm_stall_fault(self, batch) -> None:
        """Fault-registry seam: a ``delay`` rule on ``engine.stall`` wedges
        this window's landing for delay_s, as a hung device dispatch would.
        The key leads with the window kind (``decode``/``prefill``/``mixed``)
        so a rule can pin the wedge to a window class: the watchdog deadline
        scales with scheduled tokens, so only a wedge longer than that
        window's deadline is ever *detected* — matching ``decode`` keeps a
        finite delay reliably above the (small) pure-decode deadline."""
        if batch.prefills:
            kind = "mixed" if batch.decode_rows else "prefill"
        else:
            kind = "decode"
        rule = faults.active(
            "engine.stall", f"{kind}:{next(self._window_seq)}")
        if rule is not None and rule.kind == faults.DELAY:
            batch.stall_inject_s = rule.delay_s

    async def _landing(self, batch, fut):
        inject = getattr(batch, "stall_inject_s", 0.0)
        if inject:
            await asyncio.sleep(inject)  # seeded engine.stall wedge
        return await fut

    @staticmethod
    def _swallow_future(fut) -> None:
        """Detach from a wedged future: request cancellation and retrieve
        any late exception so abandoned windows never log
        'exception was never retrieved'."""
        fut.cancel()
        fut.add_done_callback(
            lambda f: f.exception() if not f.cancelled() else None
        )

    def _shape_bucket(self, kind: str, n: int) -> int:
        """Bucket used for stall attribution; the JAX engine maps through
        its dispatch bucket ladders."""
        return n

    def _quarantine_shape(self, cls) -> None:
        if cls not in self._shape_quarantine:
            self._shape_quarantine.add(cls)
            log.warning(
                "stall watchdog: quarantined shape class %s:%s", *cls
            )

    def _batch_shape_classes(self, batch) -> set:
        classes = set()
        for chunk in batch.prefills:
            classes.add(
                ("prefill", self._shape_bucket("prefill", chunk.length))
            )
        if batch.decode_rows:
            classes.add(
                ("decode", self._shape_bucket("decode",
                                              len(batch.decode_rows)))
            )
        return classes

    def _on_stall(self, batches) -> None:
        """A landing blew its deadline. Attribute the wedge to the head
        window's shape classes (cross-checked against the compile watchdog's
        last label in the log line), quarantine them, recover every touched
        seat, and track the streak toward declaring the worker dead."""
        self.num_stalls += 1
        self._stall_streak += 1
        classes = self._batch_shape_classes(batches[0])
        label = ""
        if self.obs is not None:
            try:
                from ..observability import compilewatch
                snap = compilewatch.snapshot()
                label = snap.get("last_compile_key", "") or ""
            except Exception:
                label = ""
        log.error(
            "dispatch stall: landing blew its deadline (shape classes %s, "
            "last compile %r, streak %d/%d)",
            sorted(classes), label, self._stall_streak,
            self.config.stall_dead_threshold,
        )
        for cls in classes:
            self._quarantine_shape(cls)
        self._recover_batches(batches)
        if self._stall_streak >= self.config.stall_dead_threshold:
            self.stall_dead = True
            log.error(
                "stall streak hit %d — declaring worker dead",
                self._stall_streak,
            )
            for seq in list(self._seqs.values()):
                if seq.status != SeqStatus.FINISHED:
                    self._ap_mark_dead(seq.slot)
                    self.scheduler.abort(seq, "error")
                    self._emit_finish(seq, "error")

    def _recover_batches(self, batches) -> None:
        """Cancel wedged windows: discard every pending they registered
        (mirroring _abort_batch), then requeue each touched live seat for
        journal replay — a recompute preemption whose 'journal' is the
        seq's own prompt + emitted tokens, giving byte-identical resumption
        under the seq's seed. Seats over stall_seq_retries fail instead."""
        touched: Dict[str, SchedSeq] = {}
        for batch in batches:
            for chunk in batch.prefills:
                self.scheduler.on_tokens_discarded(
                    chunk.seq, 0, first=chunk.final, prompt=chunk.length
                )
                touched[chunk.seq.seq_id] = chunk.seq
            for row in batch.decode_rows:
                self.scheduler.on_tokens_discarded(row.seq, row.accepted)
                touched[row.seq.seq_id] = row.seq
        for seq in touched.values():
            if seq.status == SeqStatus.FINISHED or seq.pending_total != 0:
                continue
            retries = self._stall_retries.get(seq.seq_id, 0) + 1
            self._stall_retries[seq.seq_id] = retries
            if retries > self.config.stall_seq_retries:
                self._ap_mark_dead(seq.slot)
                self.scheduler.abort(seq, "error")
                self._emit_finish(seq, "error")
                continue
            if seq.status is SeqStatus.WAITING:
                continue  # never held blocks — already queued for replay
            slot = self.scheduler.preempt_recompute(seq)
            self._ap_mark_dead(slot)

    # ---------------------- HBM-pressure ladder ------------------------

    def _pressure_tick(self) -> None:
        """Graduated response to KV-pool pressure, one check per loop pass:
        rung 1 spills the coldest seat (recompute preemption — its sealed
        blocks stay evictable in the prefix cache / kvbm host tier), rung 2
        pauses speculative decoding (frees draft lookahead), rung 3 sheds
        new admissions. Rungs release with pressure_release hysteresis so
        the ladder doesn't flap at a threshold."""
        cfg = self.config
        spill_t = cfg.pressure_spill_threshold
        spec_t = cfg.pressure_spec_threshold
        shed_t = cfg.pressure_shed_threshold
        if spill_t <= 0 and spec_t <= 0 and shed_t <= 0:
            return
        usage = self.scheduler.pool.usage
        release = cfg.pressure_release
        if shed_t > 0:
            if not self.pressure_shedding and usage >= shed_t:
                self.pressure_shedding = True
                log.warning(
                    "pressure ladder: shedding admissions "
                    "(pool usage %.2f >= %.2f)", usage, shed_t,
                )
            elif self.pressure_shedding and usage < shed_t - release:
                self.pressure_shedding = False
                log.info(
                    "pressure ladder: admissions reopened (pool usage %.2f)",
                    usage,
                )
        if spec_t > 0:
            if not self._pressure_spec_paused and usage >= spec_t:
                self._pressure_spec_paused = True
                self._pause_spec()
            elif self._pressure_spec_paused and usage < spec_t - release:
                self._pressure_spec_paused = False
                self._resume_spec()
        if self._pressure_spill_cool > 0:
            self._pressure_spill_cool -= 1
        if (spill_t > 0 and usage >= spill_t
                and self._pressure_spill_cool == 0):
            victim = self.scheduler._pick_victim(None)
            if victim is not None and victim.pending_total == 0:
                slot = self.scheduler.preempt_recompute(victim)
                self._ap_mark_dead(slot)
                self.num_pressure_spills += 1
                # cooldown bounds churn: the spilled seat re-prefills
                # (mostly prefix hits) before another spill is considered
                self._pressure_spill_cool = 4
                log.info(
                    "pressure ladder: spilled seq %s (pool usage %.2f)",
                    victim.seq_id, usage,
                )
        self.pressure_level = (
            3 if self.pressure_shedding
            else 2 if self._pressure_spec_paused
            else 1 if (spill_t > 0 and usage >= spill_t)
            else 0
        )
        if self.pressure_level > self.pressure_peak:
            self.pressure_peak = self.pressure_level

    def _pause_spec(self) -> None:
        """Rung 2 hook; the JAX engine narrows the spec plan window."""

    def _resume_spec(self) -> None:
        pass

    # --------------------- preemption / evacuation ---------------------
    # (runtime.preemption drives these: park a decoding seat, wait for its
    #  inflight windows to land, stream its KV to a peer, finish it here)

    def evacuable_seats(self) -> List[SchedSeq]:
        """Decoding seats whose KV is worth moving (prefill complete).
        PREFILL/WAITING seats are cheaper to re-prefill at the destination
        than to stream mid-build."""
        return [s for s in self.scheduler.running
                if s.status is SeqStatus.RUNNING and s.prefill_done]

    def park_for_evacuation(self, seq_id: str) -> Optional[SchedSeq]:
        """Freeze a seat for KV evacuation: the scheduler plans no new
        windows for it and never picks it as a recompute victim, so its
        blocks stay byte-stable while the transfer reads them."""
        seq = self._seqs.get(seq_id)
        if seq is None or seq.status is not SeqStatus.RUNNING:
            return None
        seq.status = SeqStatus.EVACUATING
        return seq

    def unpark(self, seq: SchedSeq) -> None:
        """Abort an evacuation: the seat resumes decoding locally."""
        if seq.status is SeqStatus.EVACUATING:
            seq.status = SeqStatus.RUNNING
            self._wake.set()

    async def wait_quiesced(
        self, seq: SchedSeq, timeout_s: float = 10.0
    ) -> bool:
        """Wait until none of the seat's tokens are in an inflight window —
        only then is its KV byte-stable and safe to read."""
        deadline = time.monotonic() + timeout_s
        while seq.pending_total > 0:
            if time.monotonic() >= deadline:
                return False
            await asyncio.sleep(0.005)
        return True

    def finish_evacuated(self, seq: SchedSeq) -> None:
        """The seat now lives on the receiving worker: kill the device seat
        and close the local stream with finish_reason ``evacuated``."""
        if seq.status is SeqStatus.FINISHED:
            return
        self._ap_mark_dead(seq.slot)
        self.scheduler.abort(seq, "evacuated")
        self._emit_finish(seq, "evacuated")

    async def _run_loop_sync(self) -> None:
        while not self._stopped:
            self._pressure_tick()
            batch = self.scheduler.schedule()
            self._mark_preempted_seats(batch)
            if batch.is_empty:
                # a waiting request that can never fit (pool smaller than its
                # prompt) would hang forever — fail it rather than deadlock
                if self.scheduler.waiting and not self.scheduler.running:
                    seq = self.scheduler.waiting[0]
                    log.error("seq %s cannot fit in KV pool — failing",
                              seq.seq_id)
                    self.scheduler.abort(seq, "error")
                    self._emit_finish(seq, "error")
                    continue
                # clear BEFORE the kvbm drain: a submit() arriving during the
                # drain's awaits sets _wake, which must survive to the wait()
                self._wake.clear()
                if self.kvbm is not None:
                    try:  # going idle: drain the offload backlog
                        while (not self._wake.is_set()
                               and await self.kvbm.tick()):
                            pass
                    except Exception:
                        log.exception("kvbm idle drain failed")
                if self._stopped:
                    return
                await self._wake.wait()
                continue
            self._arm_stall_fault(batch)
            inner = asyncio.ensure_future(self._execute_batch_async(batch))
            try:
                results = await asyncio.wait_for(
                    self._landing(batch, inner), self._stall_deadline(batch)
                )
            except asyncio.TimeoutError:
                self._swallow_future(inner)
                self._on_stall([batch])
                continue
            except Exception:
                log.exception("engine step failed; aborting scheduled seqs")
                # _abort_batch also clears the speculative pendings that
                # schedule() registered — plain abort would park the seqs
                # as never-reaped zombies, leaking blocks and ring slots
                self._abort_batch(batch)
                continue
            self._stall_streak = 0
            try:
                self._postprocess(batch, results)
            except Exception:
                # bookkeeping must never kill the step loop — every queued
                # request would hang forever
                log.exception("postprocess failed")
            self._flush_kv_events()
            if self.kvbm is not None:
                try:
                    await self.kvbm.tick()
                except Exception:
                    log.exception("kvbm offload tick failed")

    def _postprocess(self, batch, results) -> None:
        """Apply step results. Decode samples are per-seq token WINDOWS
        (length >= 1); tokens after a mid-window finish are discarded (and
        their speculative pendings cleared so zombie seqs get reaped)."""
        prefill_samples, decode_samples = results
        self.num_steps += 1
        for chunk, sampled in zip(batch.prefills, prefill_samples):
            seq = chunk.seq
            if seq.status == SeqStatus.FINISHED:
                # aborted while the chunk was in flight
                self.scheduler.on_tokens_discarded(
                    seq, 0, first=chunk.final, prompt=chunk.length
                )
                continue
            self.scheduler.on_prefill_executed(
                chunk, sampled if chunk.final else None
            )
            if chunk.final:
                self._emit_token(seq)
        for i, row in enumerate(batch.decode_rows):
            seq = row.seq
            window = decode_samples[i]
            if isinstance(window, int):
                window = [window]
            applied = 0
            for tok in window[:row.accepted]:
                if seq.status == SeqStatus.FINISHED:
                    break  # aborted / stopped mid-window
                self.scheduler.on_decode_executed(seq, tok)
                applied += 1
                self._emit_token(seq)
            if applied < row.accepted:
                self.scheduler.on_tokens_discarded(
                    seq, row.accepted - applied
                )
            if seq.status == SeqStatus.FINISHED:
                self._ap_mark_dead(row.slot)

    def _emit_token(self, seq: SchedSeq) -> None:
        self.num_generated_tokens += 1
        if seq.t_first_token is None:
            seq.t_first_token = time.monotonic()
        reason = self.scheduler.check_stop(seq)
        out = StepOutput(
            request_id=seq.seq_id,
            token_id=seq.output_ids[-1],
            index=len(seq.output_ids) - 1,
            finished=reason is not None,
            finish_reason=reason,
            num_prompt_tokens=seq.prompt_len,
        )
        if reason is not None:
            self.scheduler.finish(seq, reason)
        q = self._queues.get(seq.seq_id)
        if q is not None:
            q.put_nowait(out)

    def _emit_finish(self, seq: SchedSeq, reason: str) -> None:
        q = self._queues.get(seq.seq_id)
        if q is not None:
            q.put_nowait(StepOutput(
                request_id=seq.seq_id,
                token_id=seq.output_ids[-1] if seq.output_ids else -1,
                index=max(0, len(seq.output_ids) - 1),
                finished=True,
                finish_reason=reason,
                num_prompt_tokens=seq.prompt_len,
            ))

    # ------------------------- kv events -------------------------------

    def _on_kv_event(self, event: KvEvent) -> None:
        self._pending_events.append(event.to_dict())
        if len(self._pending_events) > 10000:
            del self._pending_events[:5000]
        if self.kvbm is not None:
            self.kvbm.on_pool_event(event)
        if self.prefix is not None:
            self.prefix.on_pool_event(event)

    def _flush_kv_events(self) -> None:
        if self.kv_event_sink is None:
            return
        events, self._pending_events = self._pending_events, []
        for e in events:
            try:
                self.kv_event_sink(e)
            except Exception:
                log.exception("kv event sink failed")

    def drain_kv_events(self) -> List[dict]:
        events, self._pending_events = self._pending_events, []
        return events


class InferenceEngine(EngineCore):
    """The JAX device engine: jitted unified prefill/decode steps over a
    paged HBM KV cache, dispatched from a dedicated executor thread so the
    event loop never blocks on XLA."""

    def __init__(
        self,
        model_config: ModelConfig,
        engine_config: EngineConfig,
        params: Optional[model_lib.Params] = None,
        seed: int = 0,
        devices: Optional[list] = None,
    ):
        # attention autotune, BEFORE any step fn is built: the impl probe
        # (attention_impl="auto" times Pallas vs einsum on the live
        # backend) plus per-shape-class (q_tile, kv_tile) resolution —
        # explicit config > persisted cache (DYNTPU_AUTOTUNE_CACHE) >
        # on-TPU sweep > kernel defaults
        from .autotune import autotune_attention
        engine_config, self.attention_impl_choice = autotune_attention(
            model_config, engine_config
        )
        # adaptive bucket ladders (engine/ladder.py); built after the
        # recorder below when enabled, {} keeps every bucketing call on
        # the static grid
        self._ladders: Dict[str, Any] = {}
        if engine_config.prefill_chunk_tokens > 0:
            pct = max(engine_config.prefill_chunk_tokens,
                      engine_config.block_size)
            cap = min(pct, max(engine_config.prefill_buckets))
            bucket = min(
                (b for b in engine_config.prefill_buckets if b >= cap),
                default=max(engine_config.prefill_buckets),
            )
            log.info(
                "chunked prefill: prompts admitted in %d-token chunks "
                "interleaved with decode", cap,
            )
            if bucket != cap:
                # every chunk pads up to a compiled bucket; a cap off the
                # bucket grid silently burns the difference each dispatch
                log.warning(
                    "prefill_chunk_tokens=%d is not a prefill bucket — "
                    "chunks pad to the %d bucket (%d wasted tokens each); "
                    "consider a bucket-sized cap %r",
                    cap, bucket, bucket - cap,
                    engine_config.prefill_buckets,
                )
        super().__init__(engine_config)
        self.model_config = model_config
        self.pp = engine_config.pp_stages
        if params is None:
            params = model_lib.init_params(
                jax.random.PRNGKey(seed), model_config
            )
        self._sp_prefill_fn = None
        self._mm_prefill_fn = None  # built lazily on the first mm request
        self.num_sp_prefills = 0
        self.num_mm_prefills = 0
        if self.pp > 1:
            # pipeline-parallel serving: layers stage-sharded over a pp
            # mesh, stacked cache, GPipe-microbatched unified step
            from ..parallel import pp_serving

            self.mesh = pp_serving.make_pp_mesh(self.pp, devices)
            self.params = jax.device_put(
                params, pp_serving.pp_param_shardings(self.mesh,
                                                      model_config)
            )
            self.cache = jax.device_put(
                pp_serving.init_pp_cache(model_config, engine_config),
                pp_serving.pp_cache_shardings(self.mesh, model_config),
            )
            self._step_fn = compilewatch.label(
                pp_serving.make_pp_step_fn(
                    model_config, engine_config, self.mesh,
                    engine_config.pp_microbatches,
                ),
                "pp_step",
            )
            if engine_config.decode_steps > 1:
                log.warning("decode_steps > 1 is unsupported with "
                            "pp_stages — running single-step decode")
        else:
            self.mesh = model_lib.make_mesh(
                engine_config.mesh_shape, devices
            )
            # quantize-at-init for random/host params; params streamed by
            # load_hf_params_sharded arrive already quantized (dict
            # leaves) and pass through unchanged
            params = quant.quantize_params(
                params, engine_config.weight_dtype
            )
            self.params = model_lib.shard_params(
                params, self.mesh, model_config,
                engine_config.weight_dtype,
            )
            self.cache = model_lib.shard_cache(
                model_lib.init_cache(model_config, engine_config),
                self.mesh, model_config, engine_config.kv_dtype,
            )
            self._step_fn = model_lib.make_step_fn(
                model_config, engine_config, self.mesh
            )
            # pipelined serving path: packed ring prefill + autopilot
            # decode windows running on device-resident control state
            self._window_K = max(1, engine_config.decode_steps)
            self._ap_Wcap = engine_config.max_blocks_per_seq
            self._ap_window_fn, self._ap_delta_fn = (
                model_lib.make_autopilot_fns(
                    model_config, engine_config, self._window_K,
                    self._ap_Wcap, self.mesh,
                )
            )
            # speculative decoding: drafter history + draft/verify window
            self._spec_k = 0
            self._spec_hist_cap = 0
            self._spec_auto_disabled = False
            if engine_config.spec_mode == "ngram":
                self._spec_k = engine_config.spec_k
                self._spec_hist_cap = (engine_config.spec_hist_cap
                                       or engine_config.max_model_len)
                self._spec_window_fn, self._spec_hist_fill_fn = (
                    model_lib.make_spec_fns(
                        model_config, engine_config, self._spec_k,
                        engine_config.spec_ngram_min,
                        engine_config.spec_ngram_max, self.mesh,
                    )
                )
                from ..spec.stats import SpecDecodeStats
                self.spec_stats = SpecDecodeStats()
                # a spec window lands a DATA-DEPENDENT 1..k+1 tokens, so
                # run-ahead scheduling (which predicts the next window's
                # base) is off the table: force the synchronous loop
                if engine_config.pipeline_depth > 1:
                    log.info("spec_mode=ngram forces pipeline_depth=1")
                self.scheduler.spec_plan_window = self._spec_k + 1
            repl = layout.replicated(self.mesh)
            self._ctl = jax.device_put(
                model_lib.init_ctl(
                    engine_config, engine_config.max_num_seqs,
                    self._ap_Wcap, seed=seed + 2,
                    hist_cap=self._spec_hist_cap,
                ),
                repl,
            )
            # host mirror of per-slot device state + seat map
            self._packed_prefill_fns: Dict[Tuple[int, int], Any] = {}
            # channel-traffic counters (surfaced by bench.py)
            self.num_windows = 0
            self.num_deltas = 0
            self.num_delta_rows = 0
            self.num_cols_uploads = 0
            self.num_prefill_dispatches = 0
            self._ap: Dict[int, Dict[str, Any]] = {}
            self._ap_cols: List[int] = []       # device slot_rows content
            self._ap_rows_dev = None            # its device array
            self._ap_dead: set = set()          # slots to kill next dispatch
            self.pipeline_depth = max(1, engine_config.pipeline_depth)
            if engine_config.spec_mode == "ngram":
                self.pipeline_depth = 1
            if (engine_config.sp_prefill_threshold > 0
                    and self.mesh.devices.size > 1):
                self._sp_prefill_fn = model_lib.make_sp_ring_prefill_fn(
                    model_config, engine_config, self.mesh
                )
                self.scheduler.sp_enabled = True
        # flight recorder: per-step MFU/goodput accounting + the compile
        # watchdog. Records are stamped at dispatch/landing on arrays the
        # fetcher already syncs — no extra host round-trips.
        if env_flag("DYNTPU_OBS_ENABLED", True):
            dev0 = self.mesh.devices.flat[0]
            self.obs = StepStats(
                FlopsModel(model_config),
                n_chips=int(self.mesh.devices.size),
                peak_flops=obs_flops.peak_flops(
                    getattr(dev0, "device_kind", ""),
                    getattr(dev0, "platform", "cpu"),
                    # quantized weights run the matmuls at the int8/fp8
                    # roofline — MFU against the bf16 peak would flatter
                    engine_config.weight_dtype
                    if quant.is_quantized(engine_config.weight_dtype)
                    else model_config.dtype,
                ),
                window_s=env_float("DYNTPU_OBS_WINDOW_S", 10.0),
                jsonl_path=env_str("DYNTPU_OBS_STEPSTATS_PATH", ""),
            )
            compilewatch.install()
        # waste-driven adaptive bucket ladders: consume the recorder's
        # per-bucket occupancy, split hot rungs / retire cold ones under
        # an explicit compile budget. Needs the recorder (occupancy
        # source) and the single-engine path (pp keeps static buckets).
        if (self.obs is not None and self.pp == 1
                and (engine_config.adaptive_buckets
                     or env_flag("DYNTPU_LADDER_ENABLED", False))):
            from .ladder import BucketLadder
            budget = engine_config.ladder_compile_budget
            self._ladders = {
                # decode windows and spec verify windows share the row
                # bucket grid (and its compiled programs)
                "decode": BucketLadder(
                    "decode", engine_config.decode_buckets,
                    kinds=(DECODE, SPEC_VERIFY),
                    compile_budget=budget, step=8,
                ),
                "prefill": BucketLadder(
                    "prefill", engine_config.prefill_buckets,
                    kinds=(PREFILL,),
                    compile_budget=budget, step=16,
                ),
            }
            # the scheduler snaps chunked-prefill caps onto live rungs
            self.scheduler.prefill_ladder = self._ladders["prefill"]
            log.info(
                "adaptive bucket ladders on: budget=%d rungs decode=%r "
                "prefill=%r", budget, engine_config.decode_buckets,
                engine_config.prefill_buckets,
            )
        self._rng = jax.random.PRNGKey(seed + 1)
        # one-shot einsum rebuild when the largest decode bucket stalls
        self._stall_einsum_fallback = False
        self._encode_fn = None  # built lazily on the first embed()
        self._mm_ring_fn = None  # lazy (pipelined mm prefill)
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-step"
        )
        # fetches (device_get of sampled-token handles) run OFF the
        # dispatch thread on the batching fetcher: a fetch is a host sync
        # (~64 ms+ on remote-PJRT) and must never delay the next window's
        # enqueue; grouped gets keep the landing rate above the K=1
        # window rate.
        self._fetcher = _BatchingFetcher(
            self._unpack_results, on_sync=self._count_fetch_sync
        )
        # multi-host: the leader's broadcaster observes every executed step
        # so followers can replay the identical jitted call sequence
        # (parallel/multihost.py); called on the executor thread
        self.step_sink: Optional[Callable[[str, Dict[str, np.ndarray]],
                                          None]] = None
        if self.pp > 1:
            # the transfer ops assume the per-layer list cache; disagg and
            # KVBM on a pp engine are future work
            self._kv_extract = self._kv_inject = None
        else:
            self._kv_extract, self._kv_inject = model_lib.make_kv_ops(
                engine_config, self.mesh
            )

    def _shutdown_executor(self) -> None:
        self._executor.shutdown(wait=False)
        self._fetcher.stop()
        if self.obs is not None:
            self.obs.close()

    def _ap_mark_dead(self, slot: int) -> None:
        if self.pp == 1 and slot >= 0 and (
                slot in self._ap or slot in self._ap_cols):
            self._ap_dead.add(slot)

    # ------------------ KV block transfer (disagg) ---------------------
    # Both run on the single step executor thread, serialising them with
    # step execution — the cache buffer is donated every step, so nothing
    # may touch it concurrently.

    async def extract_kv_blocks(self, block_ids) -> Dict[str, np.ndarray]:
        """Gather arbitrary physical blocks to host memory ([L, N, KV, bs,
        hd]). The id list is padded to a power of two (pads gather the trash
        block) so XLA compiles O(log N) program variants, and the pad is
        sliced off."""
        if self._kv_extract is None:
            raise RuntimeError("KV block transfer unsupported on a "
                               "pipeline-parallel engine")
        loop = asyncio.get_running_loop()
        n = len(block_ids)
        padded = np.zeros((_pow2_bucket(n),), np.int32)
        padded[:n] = block_ids

        def _ex():
            data = self._kv_extract(self.cache, padded)
            # quantized caches carry "ks"/"vs" scale planes alongside the
            # pages; slice the pad off every key uniformly
            # D2H is the point here: extract feeds the kvbm host tier /
            # the relay, off the step path
            data = jax.device_get(data)  # dynalint: disable=DT102
            return {key: np.asarray(arr)[:, :n]
                    for key, arr in data.items()}

        return await loop.run_in_executor(self._executor, _ex)

    async def inject_kv_blocks(
        self, block_ids, data: Dict[str, np.ndarray],
        *, seq_id: Optional[str] = None, epoch: Optional[int] = None,
    ) -> None:
        """Scatter per-block KV into physical blocks (pads scatter into the
        trash block, which absorbs garbage by design).

        With ``seq_id``/``epoch`` the reservation is re-validated *inside*
        the executor callable — immediately before the donated write — so a
        reservation recycled mid-flight is rejected, never scattered."""
        if self._kv_inject is None:
            raise RuntimeError("KV block transfer unsupported on a "
                               "pipeline-parallel engine")
        loop = asyncio.get_running_loop()
        n = len(block_ids)
        m = _pow2_bucket(n)
        padded = np.zeros((m,), np.int32)
        padded[:n] = block_ids
        if m != n:

            def _pad(a: np.ndarray) -> np.ndarray:
                pad_shape = list(a.shape)
                pad_shape[1] = m - n
                return np.concatenate(
                    [a, np.zeros(pad_shape, a.dtype)], axis=1
                )

            data = {key: _pad(a) for key, a in data.items()}

        def _in():
            if epoch is not None and not self.reservation_valid(seq_id, epoch):
                from ..disagg.ici import StaleEpochError

                raise StaleEpochError(
                    f"reservation {seq_id!r} epoch {epoch} is stale"
                )
            self.cache = self._kv_inject(self.cache, padded, data)

        await loop.run_in_executor(self._executor, _in)

    async def extract_kv(self, seq) -> Dict[str, np.ndarray]:
        """Gather a held sequence's KV blocks to host memory."""
        return await self.extract_kv_blocks(seq.block_table)

    async def inject_kv(self, seq, data: Dict[str, np.ndarray],
                        epoch: Optional[int] = None) -> None:
        """Scatter received KV into a reserved sequence's blocks."""
        await self.inject_kv_blocks(
            seq.block_table, data, seq_id=seq.seq_id, epoch=epoch
        )

    # ----------------------- embeddings (encode) -----------------------

    async def embed(self, token_ids_batch: List[List[int]]) -> List[List[float]]:
        """Encode-only step for ``/v1/embeddings``: mean-pooled, normalised
        final hidden states. Runs on the step executor thread (serialised
        with generation steps). Inputs are bucketed to powers of two so XLA
        compiles O(log T) encode programs."""
        if self._encode_fn is None:
            self._encode_fn = model_lib.make_encode_fn(
                self.model_config, None if self.pp > 1 else self.mesh,
                self.config.weight_dtype,
            )
        loop = asyncio.get_running_loop()

        for ids in token_ids_batch:
            if not ids:
                raise ValueError("empty embedding input")
            if len(ids) >= self.config.max_model_len:
                raise ValueError(
                    f"embedding input length {len(ids)} exceeds "
                    f"max_model_len {self.config.max_model_len}"
                )

        def _run() -> List[List[float]]:
            # group same-T-bucket inputs into one batched forward + one
            # device_get (the (B, T) buckets are both pow2, so compile
            # count stays O(log B * log T)); the step-executor thread is
            # shared with generation, so fewer dispatches = less decode
            # stall
            out: List[Optional[List[float]]] = [None] * len(token_ids_batch)
            groups: Dict[int, List[int]] = {}
            for i, ids in enumerate(token_ids_batch):
                groups.setdefault(_pow2_bucket(len(ids)), []).append(i)
            for T, idxs in groups.items():
                B = _pow2_bucket(len(idxs))
                tokens = np.zeros((B, T), np.int32)
                positions = np.full((B, T), -1, np.int32)
                for row, i in enumerate(idxs):
                    ids = token_ids_batch[i]
                    tokens[row, :len(ids)] = ids
                    positions[row, :len(ids)] = np.arange(len(ids))
                vecs = np.asarray(jax.device_get(
                    self._encode_fn(self.params, tokens, positions)
                ))
                for row, i in enumerate(idxs):
                    out[i] = vecs[row].tolist()
            return out  # type: ignore[return-value]

        return await loop.run_in_executor(self._executor, _run)

    async def embed_endpoint(self, request: Any, context: Context):
        """Wire adapter for the worker's ``embed`` endpoint."""
        vectors = await self.embed(
            [list(ids) for ids in request["token_ids_batch"]]
        )
        yield {"embeddings": vectors}

    def attach_kvbm(self, config=None, remote=None):
        """Enable the multi-tier block manager on this engine (optionally
        with a G4 remote tier)."""
        if self.pp > 1:
            raise RuntimeError("KVBM unsupported on a pipeline-parallel "
                               "engine (stacked cache has no transfer ops)")
        from ..kvbm.manager import KvbmConfig, KvbmManager

        self.kvbm = KvbmManager(self, config or KvbmConfig(), remote=remote)
        return self.kvbm

    # --------------------- device execution ----------------------------

    async def _execute_batch_async(self, batch) -> Tuple[List[int], List[int]]:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, self._execute_batch, batch
        )

    async def _dispatch_batch_async(self, batch):
        """Pipelined path: enqueue the batch's jitted calls on the dispatch
        thread (no sync), then hand the sampled-token handles to the
        batching fetcher. Returns the asyncio future of the results."""
        loop = asyncio.get_running_loop()
        handles = await loop.run_in_executor(
            self._executor, self._dispatch_batch, batch
        )
        self._fetcher.ensure_started()
        return self._fetcher.submit(loop, batch, handles)

    def _execute_batch(self, batch) -> Tuple[List[int], List[int]]:
        """Synchronous execution (pipeline_depth=1 / pp engines): dispatch
        then fetch in one executor turn."""
        if self.pp == 1:
            return self._fetch_results(batch, self._dispatch_batch(batch))
        prefill_samples: List[int] = []
        for chunk in batch.prefills:
            prefill_samples.append(self._run_prefill(chunk))
        decode_samples: List[List[int]] = []
        if batch.decodes:
            decode_samples = self._run_decode(batch)
        return prefill_samples, decode_samples

    def _dispatch_batch(self, batch):
        """Executor thread: build arrays + enqueue every jitted call for
        this window. NO host sync anywhere in here. Seat kills (finished,
        aborted, or preempted seqs whose blocks are recycling) flush FIRST
        so the in-order device queue applies them before any work that
        could touch reused blocks. Preempted slots are marked by the loop
        at schedule() time — a batch can be empty yet carry preemptions."""
        self._ap_flush_kills()
        obs_out = (
            batch.obs_records if self.obs is not None
            and hasattr(batch, "obs_records") else None
        )
        prefill_handles = [
            self._dispatch_prefill(c, obs_out) for c in batch.prefills
        ]
        decode_handle = (
            self._dispatch_decode(batch.decode_rows, obs_out)
            if batch.decode_rows else None
        )
        return prefill_handles, decode_handle

    def _ap_flush_kills(self) -> None:
        """Kill dead autopilot seats (one packed delta call). The dead-set
        swap is GIL-atomic against _ap_mark_dead calls from the event
        loop; anything added after the swap rides the next dispatch."""
        dead, self._ap_dead = self._ap_dead, set()
        if not dead:
            return
        deltas = {}
        for slot in dead:
            deltas[slot] = {
                "pos": 0, "vu": 0, "tk": 0, "seed": -1, "lt": -1,
                "table": (), "temp": 0.0, "tp": 1.0,
            }
            self._ap.pop(slot, None)
        self._ap_apply_deltas(deltas)

    def _count_fetch_sync(self) -> None:
        self.num_fetch_syncs += 1

    @hot_path
    def _fetch_results(self, batch, handles):
        """Fetch thread: device_get the window's sampled tokens (the only
        host↔device sync in the serving loop) and unpack per seat."""
        prefill_handles, decode_handle = handles
        to_get = list(prefill_handles)
        if decode_handle is not None:
            to_get.append(decode_handle[0])
        # designed sync point of the non-pipelined path: exactly one
        # device_get per executed batch, counted in num_fetch_syncs
        got = jax.device_get(to_get) if to_get else []  # dynalint: disable=DT102
        if to_get:
            self.num_fetch_syncs += 1
        return self._unpack_results(batch, handles, got)

    @hot_path
    def _unpack_results(self, batch, handles, got):
        """Map fetched arrays back to per-seat sample lists. Decode sample
        columns follow the device seat map captured at dispatch, which may
        order (and pad) differently than the batch's row list."""
        prefill_handles, decode_handle = handles
        prefill_samples = [
            int(np.asarray(g)[0]) for g in got[:len(prefill_handles)]
        ]
        decode_samples: List[List[int]] = []
        if decode_handle is not None:
            col_of = {}
            for col, slot in enumerate(decode_handle[1]):
                col_of.setdefault(slot, col)
            out = np.asarray(got[-1])  # [K, B] (spec: [k+3, B] packed)
            if len(decode_handle) > 2 and decode_handle[2]:
                decode_samples = self._unpack_spec(batch, out, col_of)
            else:
                for row in batch.decode_rows:
                    col = col_of[row.slot]
                    decode_samples.append([
                        int(out[k, col])
                        for k in range(min(row.accepted, out.shape[0]))
                    ])
        if self.obs is not None:
            self._obs_on_land(batch, decode_samples)
        return prefill_samples, decode_samples

    @hot_path
    def _obs_on_land(self, batch, decode_samples) -> None:
        """Stamp landing time + realized goodput on this window's records
        and commit them to the flight recorder. Runs right after the
        window's one designed device_get, on already-fetched host ints —
        no extra syncs."""
        recs = getattr(batch, "obs_records", None)
        if not recs:
            return
        t_land = time.monotonic()
        emitted = sum(len(w) for w in decode_samples)
        for rec in recs:
            rec.t_land = t_land
            if rec.kind != PREFILL:
                rec.goodput_tokens = emitted
            self.obs.commit(rec)
        recs.clear()
        if self._ladders:
            self._ladder_tick()

    @hot_path
    def _ladder_tick(self) -> None:
        """Feed the recorder's occupancy histogram to the bucket ladders
        and run one (cheap, host-int) adaptation check. Called on every
        landing; BucketLadder.min_dispatches gates actual epochs."""
        occ = self.obs.bucket_occupancy()
        for lad in self._ladders.values():
            lad.ingest(occ)
            lad.maybe_adapt()

    @hot_path
    def _unpack_spec(self, batch, out, col_of) -> List[List[int]]:
        """Spec verify window landing: packed rows 0..k are emitted token
        candidates, row k+1 n_emitted, row k+2 n_drafted. Runs on the
        (single) executor thread — spec forces the synchronous loop — so
        correcting the host mirror's pessimistic pos here is ordered
        strictly before the next dispatch."""
        kk = self._spec_k
        stats = self.spec_stats
        decode_samples: List[List[int]] = []
        win_drafted = win_accepted = 0
        for row in batch.decode_rows:
            col = col_of[row.slot]
            n = int(out[kk + 1, col])
            ndraft = int(out[kk + 2, col])
            n_use = min(n, row.accepted)
            decode_samples.append([int(out[j, col]) for j in range(n_use)])
            row.seq.spec_drafted += ndraft
            row.seq.spec_accepted += max(n - 1, 0)
            win_drafted += ndraft
            win_accepted += max(n - 1, 0)
            stats.drafted += ndraft
            stats.accepted += max(n - 1, 0)
            stats.emitted += n_use
            st = self._ap.get(row.slot)
            if st is not None and st["seq_id"] == row.seq.seq_id:
                st["pos"] = row.base + n
        stats.windows += 1
        if self.obs is not None:
            for rec in getattr(batch, "obs_records", ()):
                if rec.kind == SPEC_VERIFY:
                    rec.spec_drafted += win_drafted
                    rec.spec_accepted += win_accepted
        th = self.config.spec_auto_disable_threshold
        if (th > 0.0 and not self._spec_auto_disabled
                and stats.drafted >= self.config.spec_auto_disable_window
                and stats.acceptance_rate < th):
            # one-way: drafting is costing more verify compute than it
            # saves in syncs on this workload; fall back to plain windows
            self._spec_auto_disabled = True
            self.scheduler.spec_plan_window = None
            log.info(
                "spec decode auto-disabled: acceptance %.3f < %.3f after "
                "%d drafts", stats.acceptance_rate, th, stats.drafted,
            )
        return decode_samples

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _bucket_for(self, kind: str, n: int) -> int:
        """Bucket ``n`` on the live ladder grid for ``kind`` (adaptive
        rungs when the ladder is on, the static config grid otherwise).
        Stall-quarantined buckets route to the next rung up — a different
        compiled program doing the same work with padding."""
        lad = self._ladders.get(kind)
        if lad is not None:
            b = lad.bucket_for(n)
            grid = tuple(sorted(lad.snapshot()["rungs"]))
        else:
            cfg = self.config
            grid = (cfg.decode_buckets if kind == "decode"
                    else cfg.prefill_buckets)
            b = _bucket(n, grid)
        if self._shape_quarantine and (kind, b) in self._shape_quarantine:
            for g in grid:
                if g >= b and (kind, g) not in self._shape_quarantine:
                    return g
        return b

    def _shape_bucket(self, kind: str, n: int) -> int:
        return self._bucket_for(kind, n)

    def _quarantine_shape(self, cls) -> None:
        """When the LARGEST decode bucket wedges there is no rung to route
        to — rebuild the decode window on the einsum attention impl instead
        (a different program for the same shape class), once."""
        kind, bucket = cls
        if (self.pp == 1 and kind == "decode"
                and not self._stall_einsum_fallback):
            cfg = self.config
            grid = cfg.decode_buckets
            lad = self._ladders.get("decode")
            if lad is not None:
                grid = tuple(sorted(lad.snapshot()["rungs"]))
            if bucket >= max(grid):
                try:
                    import dataclasses as _dc
                    fb_cfg = _dc.replace(
                        cfg, attention_impl_decode="einsum"
                    )
                    self._ap_window_fn, self._ap_delta_fn = (
                        model_lib.make_autopilot_fns(
                            self.model_config, fb_cfg, self._window_K,
                            self._ap_Wcap, self.mesh,
                        )
                    )
                    self._stall_einsum_fallback = True
                    log.warning(
                        "stall watchdog: decode:%d is the largest rung — "
                        "rebuilt the decode window on the einsum attention "
                        "impl instead of quarantining it", bucket,
                    )
                    return
                except Exception:
                    log.exception(
                        "einsum fallback rebuild failed — quarantining "
                        "decode:%d (it will keep dispatching at its own "
                        "rung)", bucket,
                    )
        super()._quarantine_shape(cls)

    def _pause_spec(self) -> None:
        # pp engines never set _spec_k (spec decode is single-engine only)
        if getattr(self, "_spec_k", 0) <= 0 or self._spec_auto_disabled:
            return
        self._pressure_spec_saved = self.scheduler.spec_plan_window
        self.scheduler.spec_plan_window = None
        log.warning("pressure ladder: speculative decoding paused")

    def _resume_spec(self) -> None:
        if self._pressure_spec_saved is None:
            return
        if not self._spec_auto_disabled:
            self.scheduler.spec_plan_window = self._pressure_spec_saved
        self._pressure_spec_saved = None
        log.info("pressure ladder: speculative decoding resumed")

    def _prefill_arrays(self, chunk: PrefillChunk, use_sp: bool):
        cfg = self.config
        seq = chunk.seq
        if chunk.length <= max(cfg.prefill_buckets) and not use_sp:
            T = self._bucket_for("prefill", chunk.length)
        else:
            # sp full-prompt chunks (and any oversized chunk) bucket to the
            # next power of two — always divisible by the sp ring size
            T = _pow2_bucket(chunk.length)
        # only the blocks this chunk can touch: keeps W a function of the
        # chunk shape alone, so lookahead-grown tables don't mint new
        # (T, W) programs mid-serving (remote compiles are ~50 s)
        bs = cfg.block_size
        nb = min((chunk.start + chunk.length + bs - 1) // bs,
                 len(seq.block_table))
        W = _pow2_bucket(nb, cfg.max_blocks_per_seq)
        tokens = np.zeros((1, T), np.int32)
        positions = np.full((1, T), -1, np.int32)
        all_toks = seq.all_tokens()
        tokens[0, :chunk.length] = all_toks[
            chunk.start:chunk.start + chunk.length
        ]
        positions[0, :chunk.length] = np.arange(
            chunk.start, chunk.start + chunk.length
        )
        tables = np.zeros((1, W), np.int32)
        tables[0, :nb] = seq.block_table[:nb]
        return {
            "tokens": tokens, "positions": positions, "tables": tables,
            "last_idx": np.array([chunk.length - 1], np.int32),
            "temp": np.array([seq.temperature], np.float32),
            "top_k": np.array([seq.top_k], np.int32),
            "top_p": np.array([seq.top_p], np.float32),
            "seeds": np.array([seq.seed], np.int32),
        }

    def _mm_chunk_rows(self, chunk: PrefillChunk):
        """(chunk-relative row, embedding index) of multimodal placeholder
        positions inside this chunk (decode never needs this — placeholders
        live in the prompt only)."""
        seq = chunk.seq
        if not seq.mm_positions:
            return []
        lo, hi = chunk.start, chunk.start + chunk.length
        return [
            (p - lo, k) for k, p in enumerate(seq.mm_positions)
            if lo <= p < hi
        ]

    @hot_path
    def _dispatch_prefill(self, chunk: PrefillChunk, obs_out=None):
        """Enqueue one prefill chunk on the ring path; returns the sampled
        handle [1] (garbage unless ``chunk.final``). No host sync."""
        cfg = self.config
        seq = chunk.seq
        self.num_prefill_dispatches += 1
        use_sp = (
            self._sp_prefill_fn is not None
            and chunk.start == 0 and chunk.final
            and chunk.length >= cfg.sp_prefill_threshold
            and not seq.mm_positions  # the sp path has no mm splicing
        )
        a = self._prefill_arrays(chunk, use_sp)
        if obs_out is not None:
            # host-known ints only — prompt tokens are goodput at dispatch;
            # context_sum = Σ attended context over the chunk's positions
            L, S = chunk.length, chunk.start
            obs_out.append(StepRecord(
                kind=PREFILL, t_dispatch=time.monotonic(),
                bucket=a["tokens"].shape[1],
                rows=1, live_rows=1,
                padded_tokens=a["tokens"].shape[1], real_tokens=L,
                goodput_tokens=L,
                context_sum=L * S + L * (L + 1) // 2,
            ))
        slot = np.array(
            [seq.slot if seq.slot >= 0 else cfg.max_num_seqs], np.int32
        )
        write = np.array([1 if chunk.final else 0], np.int32)
        mm_rows = self._mm_chunk_rows(chunk)
        if mm_rows:
            if self._mm_ring_fn is None:
                self._mm_ring_fn = model_lib.make_mm_ring_prefill_fn(
                    self.model_config, cfg, self.mesh
                )
            D = self.model_config.hidden_size
            T = a["tokens"].shape[1]
            mm_embeds = np.zeros((1, T, D), np.float32)
            mm_mask = np.zeros((1, T), bool)
            emb = np.asarray(seq.mm_embeddings, np.float32)
            for row, k in mm_rows:
                mm_embeds[0, row] = emb[k]
                mm_mask[0, row] = True
            self.num_mm_prefills += 1
            if self.step_sink is not None:
                self.step_sink("mrp", {
                    **a, "slot": slot, "write": write,
                    "mm_embeds": mm_embeds,
                    "mm_mask": mm_mask.astype(np.int32),
                })
            self.cache, new_lt, sampled = self._mm_ring_fn(
                self.params, self.cache, self._ctl["last_tok"],
                a["tokens"], a["positions"], a["tables"], a["last_idx"],
                slot, write, self._next_rng(), a["temp"], a["top_k"],
                a["top_p"], a["seeds"], mm_embeds, mm_mask,
            )
            self._ctl = {**self._ctl, "last_tok": new_lt}
            return sampled
        if use_sp:
            if self.step_sink is not None:
                self.step_sink("rsp", {**a, "slot": slot, "write": write})
            self.num_sp_prefills += 1
            self.cache, new_lt, sampled = self._sp_prefill_fn(
                self.params, self.cache, self._ctl["last_tok"],
                a["tokens"], a["positions"], a["tables"], a["last_idx"],
                slot, write, self._next_rng(), a["temp"], a["top_k"],
                a["top_p"], a["seeds"],
            )
            self._ctl = {**self._ctl, "last_tok": new_lt}
            return sampled
        # plain path: pack every int input into ONE upload (2 total with
        # the f32 pair) — prefill uploads dominate the serial channel
        T = a["tokens"].shape[1]
        W = a["tables"].shape[1]
        fn = self._packed_prefill_fns.get((T, W))
        if fn is None:
            fn = model_lib.make_packed_prefill_fn(
                self.model_config, cfg, T, W, self.mesh
            )
            self._packed_prefill_fns[(T, W)] = fn
        pint = np.zeros((1, T + W + model_lib.PP_SCALARS), np.int32)
        pint[0, :T] = a["tokens"][0]
        pint[0, T:T + W] = a["tables"][0]
        pint[0, T + W:] = (
            chunk.length, chunk.start, int(slot[0]), int(write[0]),
            seq.top_k, seq.seed,
            int(round(seq.temperature * model_lib.PP_QUANT)),
            int(round(seq.top_p * model_lib.PP_QUANT)),
        )
        if self.step_sink is not None:
            self.step_sink("pp", {"pint": pint,
                                  "tw": np.array([T, W], np.int32)})
        self.cache, new_lt, sampled = fn(
            self.params, self.cache, self._ctl["last_tok"], pint,
            self._next_rng(),
        )
        self._ctl = {**self._ctl, "last_tok": new_lt}
        return sampled

    @hot_path
    def _ap_apply_deltas(self, deltas: Dict[int, Dict[str, Any]]) -> None:
        """Pack + enqueue one control-state delta call (2 uploads total —
        on the remote-PJRT tunnel each upload is ~15 ms of serial channel
        time, so per-field arrays are unaffordable)."""
        Wcap = self._ap_Wcap
        n = _pow2_bucket(len(deltas))
        trash = self.config.max_num_seqs
        di = np.zeros((n, model_lib.CTL_I32_FIELDS + Wcap), np.int32)
        di[:, 0] = trash               # pad rows scatter to the trash slot
        di[:, 5] = -1                  # pad rows keep last_tok
        df = np.zeros((n, 2), np.float32)
        for i, (slot, d) in enumerate(sorted(deltas.items())):
            di[i, 0] = slot
            di[i, 1] = d["pos"]
            di[i, 2] = d["vu"]
            di[i, 3] = d["tk"]
            di[i, 4] = d["seed"]
            di[i, 5] = d["lt"]
            table = d["table"]
            di[i, 6:6 + len(table)] = table
            df[i, 0] = d["temp"]
            df[i, 1] = d["tp"]
        if self.step_sink is not None:
            self.step_sink("ctl", {"di": di, "df": df})
        self.num_deltas += 1
        self.num_delta_rows += len(deltas)
        self._ctl = self._ap_delta_fn(self._ctl, di, df)

    @hot_path
    def _dispatch_decode(self, rows, obs_out=None):
        """Enqueue one autopilot decode window. Steady state (same seats,
        no growth) dispatches with ZERO fresh host arrays — all control
        state is device-resident; the host sends packed deltas only on
        joins, block growth, resumes, and seat-map changes. Returns
        (samples_handle [K, B], col_map, spec) where col_map[device
        column] is the slot computed there and ``spec`` marks a packed
        spec-window handle."""
        cfg = self.config
        bs = cfg.block_size
        spec = self._spec_active()
        # spec windows land a data-dependent 1..k+1 tokens; mirror the
        # device's advance pessimistically here (max) and correct it in
        # _unpack_spec before the next dispatch (synchronous loop)
        K = (self._spec_k + 1) if spec else self._window_K
        deltas: Dict[int, Dict[str, Any]] = {}
        reset_rows: List[Any] = []
        for r in rows:
            s = r.seq
            vu = min(len(s.block_table) * bs, cfg.max_model_len)
            tlen = len(s.block_table)
            params_key = (s.temperature, s.top_k, s.top_p, s.seed)
            st = self._ap.get(r.slot)
            if (st is None or st["seq_id"] != s.seq_id
                    or st["pos"] != r.base or st["params"] != params_key):
                # join / resume / drift: reset the whole slot. lt = -1
                # keeps the ring token the producer wrote on device; a
                # host-known token (resume, inject) is pushed instead.
                deltas[r.slot] = {
                    "pos": r.base, "vu": vu, "tk": s.top_k,
                    "seed": s.seed,
                    "lt": -1 if r.tok_src else r.tok_host,
                    "table": s.block_table, "temp": s.temperature,
                    "tp": s.top_p,
                }
                reset_rows.append(r)
            elif st["vu"] != vu or st["tlen"] != tlen:
                deltas[r.slot] = {
                    "pos": r.base, "vu": vu, "tk": s.top_k,
                    "seed": s.seed, "lt": -1,
                    "table": s.block_table, "temp": s.temperature,
                    "tp": s.top_p,
                }
            # mirror the device's own advance: acc = clip(vu - pos, 0, K)
            self._ap[r.slot] = {
                "seq_id": s.seq_id, "params": params_key,
                "pos": r.base + min(max(vu - r.base, 0), K),
                "vu": vu, "tlen": tlen,
            }
        if deltas:
            self._ap_apply_deltas(deltas)
            if spec and reset_rows:
                self._spec_fill_hist(reset_rows)
        # seat map: reuse the device map only when the LIVE seats it holds
        # are exactly the scheduled set. Dead seats idle at vu=0, but a
        # LIVE slot the scheduler skipped this round (pool pressure) must
        # not keep its column — the window would advance its device pos/ring
        # token K steps behind the host mirror's back. Rebuild + upload
        # excludes it; its device state is untouched until re-scheduled.
        needed = [r.slot for r in rows]
        B = self._bucket_for("decode", len(needed))
        live = {s for s in self._ap_cols if s in self._ap}
        if (self._ap_rows_dev is None or len(self._ap_cols) != B
                or live != set(needed)):
            trash = cfg.max_num_seqs
            cols = list(needed) + [trash] * (B - len(needed))
            arr = np.asarray(cols, np.int32)
            if self.step_sink is not None:
                self.step_sink("cols", {"rows": arr})
            self._ap_cols = cols
            self.num_cols_uploads += 1
            self._ap_rows_dev = jax.device_put(arr)
        if self.step_sink is not None:
            self.step_sink("sw" if spec else "w", {})
        self.num_windows += 1
        if obs_out is not None:
            # realized goodput (emitted tokens; spec accept counts) is
            # stamped at landing — only padded/real shapes are known here
            ctx = sum(K * r.base + K * (K + 1) // 2 for r in rows)
            obs_out.append(StepRecord(
                kind=SPEC_VERIFY if spec else DECODE,
                t_dispatch=time.monotonic(),
                bucket=B,
                rows=B, live_rows=len(rows),
                padded_tokens=B * K, real_tokens=len(rows) * K,
                context_sum=ctx,
            ))
        fn = self._spec_window_fn if spec else self._ap_window_fn
        self.cache, self._ctl, samples = fn(
            self.params, self.cache, self._ctl, self._ap_rows_dev,
        )
        return samples, list(self._ap_cols), spec

    def _spec_active(self) -> bool:
        return (self._spec_k > 0 and not self._spec_auto_disabled
                and not self._pressure_spec_paused)

    def _spec_fill_hist(self, rows) -> None:
        """Inject full token histories for joining/reset seats so the
        on-device drafter has context immediately — including resumed and
        migrated sequences, whose carried tokens arrive with the request.
        One [n, Hcap+1] upload per join delta; steady-state windows extend
        the history on device with no uploads at all."""
        Hcap = self._spec_hist_cap
        trash = self.config.max_num_seqs
        n = _pow2_bucket(len(rows))
        slots = np.full((n,), trash, np.int32)
        hrows = np.full((n, Hcap + 1), -1, np.int32)
        for i, r in enumerate(rows):
            toks = r.seq.all_tokens()[:min(r.base + 1, Hcap)]
            slots[i] = r.slot
            hrows[i, :len(toks)] = toks
        if self.step_sink is not None:
            self.step_sink("sph", {"slots": slots, "hist": hrows})
        self._ctl = self._spec_hist_fill_fn(self._ctl, slots, hrows)

    # ---- legacy synchronous path (pipeline-parallel engines only) ----

    def _run_prefill(self, chunk: PrefillChunk) -> int:
        a = self._prefill_arrays(chunk, use_sp=False)
        mm_rows = self._mm_chunk_rows(chunk)
        if mm_rows:
            if self._mm_prefill_fn is None:
                self._mm_prefill_fn = model_lib.make_mm_prefill_fn(
                    self.model_config, self.config, self.mesh
                )
            D = self.model_config.hidden_size
            T = a["tokens"].shape[1]
            mm_embeds = np.zeros((1, T, D), np.float32)
            mm_mask = np.zeros((1, T), bool)
            emb = np.asarray(chunk.seq.mm_embeddings, np.float32)
            for row, k in mm_rows:
                mm_embeds[0, row] = emb[k]
                mm_mask[0, row] = True
            self.num_mm_prefills += 1
            self.cache, sampled = self._mm_prefill_fn(
                self.params, self.cache, a["tokens"], a["positions"],
                a["tables"], a["last_idx"], self._next_rng(), a["temp"],
                a["top_k"], a["top_p"], a["seeds"], mm_embeds, mm_mask,
            )
            # sync fallback path (no batching fetcher): one pull per step
            return int(np.asarray(jax.device_get(sampled))[0])  # dynalint: disable=DT101,DT102
        if self.step_sink is not None:
            self.step_sink("p", {**a})
        self.cache, sampled = self._step_fn(
            self.params, self.cache, a["tokens"], a["positions"],
            a["tables"], a["last_idx"], self._next_rng(), a["temp"],
            a["top_k"], a["top_p"], a["seeds"],
        )
        # sync fallback path (no batching fetcher): one pull per step
        return int(np.asarray(jax.device_get(sampled))[0])  # dynalint: disable=DT101,DT102

    def _run_decode(self, batch) -> List[List[int]]:
        cfg = self.config
        rows = batch.decode_rows
        B = self._bucket_for("decode", len(rows))
        W = _pow2_bucket(
            max(len(r.seq.block_table) for r in rows),
            cfg.max_blocks_per_seq,
        )
        tokens = np.zeros((B, 1), np.int32)
        positions = np.full((B, 1), -1, np.int32)
        tables = np.zeros((B, W), np.int32)
        temp = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        seeds = np.full((B,), -1, np.int32)
        for i, r in enumerate(rows):
            s = r.seq
            tokens[i, 0] = r.tok_host
            positions[i, 0] = r.base
            tables[i, :len(s.block_table)] = s.block_table
            temp[i] = s.temperature
            top_k[i] = s.top_k
            top_p[i] = s.top_p
            seeds[i] = s.seed
        last_idx = np.zeros((B,), np.int32)
        if self.step_sink is not None:
            self.step_sink("d", {
                "tokens": tokens, "positions": positions, "tables": tables,
                "last_idx": last_idx, "temp": temp, "top_k": top_k,
                "top_p": top_p, "seeds": seeds,
            })
        self.cache, sampled = self._step_fn(
            self.params, self.cache, tokens, positions, tables,
            last_idx, self._next_rng(), temp, top_k, top_p, seeds,
        )
        # sync fallback path (no batching fetcher): one pull per step
        out = np.asarray(jax.device_get(sampled))  # dynalint: disable=DT102
        return [[int(out[i])] for i in range(len(rows))]
