"""Model weight loading: HF safetensors → stacked scan params, plus orbax
native checkpoints.

Role-equivalent to the weight-loading path inside the reference's engines
(vLLM loads HF checkpoints; the reference itself only ships the model card,
ref: lib/llm/src/model_card.rs:93). Our scan-stacked layout wants every
per-layer leaf stacked on a leading L axis, and JAX matmul orientation
``x @ W`` wants HF's ``[out, in]`` Linear weights transposed.

Dense (Llama 2/3) and MoE (Mixtral-style ``block_sparse_moe``) checkpoints
are supported. Loading streams tensor-by-tensor from the safetensors
memory map into preallocated stacked buffers — peak host memory is one
stacked leaf, not two copies of the checkpoint.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger
from . import quant
from .config import ModelConfig

log = get_logger("engine.weights")

Params = Dict[str, Any]


def _scale_shape(shape: tuple) -> tuple:
    """Per-output-channel scale shape for a weight of ``shape``: the
    contraction axis (-2) collapses to 1, ``keepdims`` style."""
    return shape[:-2] + (1,) + shape[-1:]

# stats from the most recent load_hf_params_sharded call (tests pin
# peak_staging_bytes to one checkpoint tensor)
last_load_stats: Dict[str, Any] = {}


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _stacked_shapes(cfg: ModelConfig) -> Dict[str, tuple]:
    hd = cfg.head_dim_
    D, H, KV, F, L, V, E = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
        cfg.intermediate_size, cfg.num_layers, cfg.vocab_size,
        cfg.num_experts,
    )
    layers = {
        "attn_norm": (L, D),
        "wq": (L, D, H * hd),
        "wk": (L, D, KV * hd),
        "wv": (L, D, KV * hd),
        "wo": (L, H * hd, D),
        "mlp_norm": (L, D),
    }
    if cfg.is_moe:
        layers.update({
            "w_router": (L, D, E),
            "w_gate": (L, E, D, F),
            "w_up": (L, E, D, F),
            "w_down": (L, E, F, D),
        })
    else:
        layers.update({
            "w_gate": (L, D, F),
            "w_up": (L, D, F),
            "w_down": (L, F, D),
        })
    return layers


def _param_shapes(cfg: ModelConfig) -> Dict[str, Any]:
    """Full param-tree shapes matching ``model.init_params(cfg)``."""
    D, V = cfg.hidden_size, cfg.vocab_size
    shapes: Dict[str, Any] = {
        "embed": (V, D),
        "layers": _stacked_shapes(cfg),
        "final_norm": (D,),
    }
    if not cfg.tie_word_embeddings:
        shapes["lm_head"] = (D, V)
    return shapes


def abstract_params(cfg: ModelConfig, mesh=None,
                    weight_dtype: str = "bf16") -> Params:
    """``jax.ShapeDtypeStruct`` tree for the param pytree — with a mesh,
    each leaf carries its ``SpecLayout`` NamedSharding, so orbax restores
    (and the streaming HF loader) land directly on device shards.  With a
    quantized ``weight_dtype`` the matmul leaves become ``{"q", "s"}``
    sub-trees (storage payload + float32 scales)."""
    import jax

    dt = jnp.dtype(cfg.dtype)
    q_dt = quant.storage_dtype(weight_dtype) \
        if quant.is_quantized(weight_dtype) else None

    def _leaf(name: str, shape: tuple):
        if q_dt is not None and quant.is_weight_leaf(name):
            return {
                "q": jax.ShapeDtypeStruct(shape, q_dt),
                "s": jax.ShapeDtypeStruct(_scale_shape(shape), jnp.float32),
            }
        return jax.ShapeDtypeStruct(shape, dt)

    shapes = _param_shapes(cfg)
    tree: Params = {
        name: ({k: _leaf(k, s) for k, s in sub.items()}
               if name == "layers" else _leaf(name, sub))
        for name, sub in shapes.items()
    }
    if mesh is not None and mesh.devices.size > 1:
        from ..parallel.layout import SpecLayout

        shardings = SpecLayout.for_mesh(mesh).param_shardings(
            mesh, cfg, weight_dtype)
        tree = jax.tree.map(
            lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
            tree, shardings,
        )
    return tree


def _dest(cfg: ModelConfig, name: str):
    """Map an HF tensor name to (leaf_path, layer_idx, expert_idx,
    transpose). Returns None for tensors we ignore (rotary inv_freq etc.)."""
    if name == "model.embed_tokens.weight":
        return ("embed", None, None, False)
    if name == "model.norm.weight":
        return ("final_norm", None, None, False)
    if name == "lm_head.weight":
        if cfg.tie_word_embeddings:
            return None
        return ("lm_head", None, None, True)
    if not name.startswith("model.layers."):
        return None
    rest = name[len("model.layers."):]
    idx, _, sub = rest.partition(".")
    i = int(idx)
    table = {
        "input_layernorm.weight": ("attn_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("mlp_norm", False),
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
        "block_sparse_moe.gate.weight": ("w_router", True),
    }
    if sub in table:
        leaf, t = table[sub]
        return (leaf, i, None, t)
    if sub.startswith("block_sparse_moe.experts."):
        erest = sub[len("block_sparse_moe.experts."):]
        eidx, _, ew = erest.partition(".")
        e = int(eidx)
        # Mixtral: w1 = gate, w3 = up, w2 = down
        emap = {"w1.weight": "w_gate", "w3.weight": "w_up",
                "w2.weight": "w_down"}
        if ew in emap:
            return (emap[ew], i, e, True)
    return None


def load_hf_params(path: str, cfg: ModelConfig,
                   weight_dtype: str = "bf16") -> Params:
    """Load an HF-format checkpoint directory (``*.safetensors``) into the
    stacked scan param tree, cast to ``cfg.dtype``.

    With a quantized ``weight_dtype``, each matmul tensor is quantized in
    numpy as it streams off the memory map — per-output-channel scales,
    the ``engine.quant`` convention — so the stacked host buffers hold the
    1-byte payload plus float32 scales, never a full-precision copy of a
    quantized leaf."""
    from safetensors import safe_open

    path = Path(path)
    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    dt = _np_dtype(cfg.dtype)
    quantized = quant.is_quantized(weight_dtype)
    q_dt = quant.np_storage_dtype(weight_dtype) if quantized else None

    def _buf(name: str, shape: tuple):
        if quantized and quant.is_weight_leaf(name):
            return {"q": np.zeros(shape, q_dt),
                    "s": np.zeros(_scale_shape(shape), np.float32)}
        return np.zeros(shape, dt)

    layers = {
        k: _buf(k, shape) for k, shape in _stacked_shapes(cfg).items()
    }
    top: Dict[str, Any] = {}
    seen = set()

    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                dest = _dest(cfg, name)
                if dest is None:
                    continue
                leaf, i, e, transpose = dest
                t = sf.get_tensor(name)
                if t.dtype == np.uint16:  # safetensors numpy bf16 fallback
                    import ml_dtypes

                    t = t.view(ml_dtypes.bfloat16)
                if transpose:
                    t = t.T
                if quantized and quant.is_weight_leaf(leaf):
                    qd = quant.quantize_np(t, weight_dtype)
                    if i is None:
                        top[leaf] = qd
                    elif e is None:
                        layers[leaf]["q"][i] = qd["q"]
                        layers[leaf]["s"][i] = qd["s"]
                    else:
                        layers[leaf]["q"][i, e] = qd["q"]
                        layers[leaf]["s"][i, e] = qd["s"]
                    seen.add((leaf, i, e))
                    continue
                t = t.astype(dt, copy=False)
                if i is None:
                    top[leaf] = np.asarray(t)
                elif e is None:
                    layers[leaf][i] = t
                else:
                    layers[leaf][i, e] = t
                seen.add((leaf, i, e))

    params: Params = {
        "embed": top["embed"],
        "layers": layers,
        "final_norm": top["final_norm"],
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = top["lm_head"]
    log.info("loaded %d tensors from %s (%d files, weight_dtype=%s)",
             len(seen), path, len(files), weight_dtype)

    def _dev(v):
        if isinstance(v, dict):
            return {kk: _dev(vv) for kk, vv in v.items()}
        return jnp.asarray(v)

    return {k: _dev(v) for k, v in params.items()}


def load_hf_params_sharded(path: str, cfg: ModelConfig, mesh,
                           weight_dtype: str = "bf16") -> Params:
    """Stream an HF safetensors checkpoint directly onto device shards.

    Each checkpoint tensor is staged on host exactly once — peak host
    memory is the single largest tensor, never a replicated copy of the
    model — then scattered into its preallocated device-sharded stacked
    buffer with a donated jitted ``.at[i].set``. The buffer keeps its
    ``SpecLayout`` layout throughout, so the engine can serve straight
    from the returned tree with zero resharding.

    With a quantized ``weight_dtype`` each matmul tensor is quantized in
    numpy right after staging (still one tensor peak), and the 1-byte
    payload + float32 scales scatter into their own sharded buffers — the
    full-precision tensor never reaches the device.
    """
    import jax
    from safetensors import safe_open

    from ..parallel.layout import SpecLayout

    path = Path(path)
    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    dt = _np_dtype(cfg.dtype)
    quantized = quant.is_quantized(weight_dtype)
    q_dt = jnp.dtype(quant.storage_dtype(weight_dtype)) if quantized else None
    shardings = SpecLayout.for_mesh(mesh).param_shardings(
        mesh, cfg, weight_dtype)

    def _zeros(shape, sharding, buf_dt):
        return jax.jit(
            lambda: jnp.zeros(shape, buf_dt), out_shardings=sharding
        )()

    def _buf(name: str, shape: tuple):
        sh = shardings["layers"][name]
        if quantized and quant.is_weight_leaf(name):
            return {
                "q": _zeros(shape, sh["q"], q_dt),
                "s": _zeros(_scale_shape(shape), sh["s"], jnp.float32),
            }
        return _zeros(shape, sh, dt)

    layers = {
        k: _buf(k, shape) for k, shape in _stacked_shapes(cfg).items()
    }
    top: Dict[str, Any] = {}

    setters: Dict[Any, Any] = {}

    def _setter(leaf: str, sub: Optional[str], with_expert: bool):
        key = (leaf, sub, with_expert)
        if key not in setters:
            sh = shardings["layers"][leaf]
            if sub is not None:
                sh = sh[sub]
            if with_expert:
                fn = lambda buf, i, e, t: buf.at[i, e].set(t)
            else:
                fn = lambda buf, i, t: buf.at[i].set(t)
            setters[key] = jax.jit(
                fn, donate_argnums=(0,), out_shardings=sh
            )
        return setters[key]

    n_seen = 0
    peak = 0
    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                dest = _dest(cfg, name)
                if dest is None:
                    continue
                leaf, i, e, transpose = dest
                t = sf.get_tensor(name)
                if t.dtype == np.uint16:  # safetensors numpy bf16 fallback
                    import ml_dtypes

                    t = t.view(ml_dtypes.bfloat16)
                if transpose:
                    t = t.T
                if quantized and quant.is_weight_leaf(leaf):
                    qd = quant.quantize_np(np.ascontiguousarray(t),
                                           weight_dtype)
                    # quantize_np stages one float32 copy of this tensor —
                    # still a one-tensor peak, just at 4 bytes/elem
                    peak = max(peak, int(t.size) * 4)
                    if i is None:
                        top[leaf] = {
                            "q": jax.device_put(qd["q"],
                                                shardings[leaf]["q"]),
                            "s": jax.device_put(qd["s"],
                                                shardings[leaf]["s"]),
                        }
                    elif e is None:
                        layers[leaf]["q"] = _setter(leaf, "q", False)(
                            layers[leaf]["q"], i, qd["q"])
                        layers[leaf]["s"] = _setter(leaf, "s", False)(
                            layers[leaf]["s"], i, qd["s"])
                    else:
                        layers[leaf]["q"] = _setter(leaf, "q", True)(
                            layers[leaf]["q"], i, e, qd["q"])
                        layers[leaf]["s"] = _setter(leaf, "s", True)(
                            layers[leaf]["s"], i, e, qd["s"])
                    n_seen += 1
                    continue
                t = np.ascontiguousarray(t.astype(dt, copy=False))
                peak = max(peak, t.nbytes)
                if i is None:
                    top[leaf] = jax.device_put(t, shardings[leaf])
                elif e is None:
                    layers[leaf] = _setter(leaf, None, False)(
                        layers[leaf], i, t
                    )
                else:
                    layers[leaf] = _setter(leaf, None, True)(
                        layers[leaf], i, e, t
                    )
                n_seen += 1

    params: Params = {
        "embed": top["embed"],
        "layers": layers,
        "final_norm": top["final_norm"],
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = top["lm_head"]
    last_load_stats.clear()
    last_load_stats.update(
        n_tensors=n_seen, n_files=len(files),
        peak_staging_bytes=int(peak),
    )
    log.info(
        "streamed %d tensors from %s onto %d devices "
        "(peak host staging %.1f MiB)",
        n_seen, path, mesh.devices.size, peak / 2**20,
    )
    return params


def model_config_from_hf(path: str) -> ModelConfig:
    """Build a ModelConfig from an HF ``config.json``."""
    with open(Path(path) / "config.json") as f:
        c = json.load(f)
    return ModelConfig(
        vocab_size=c["vocab_size"],
        hidden_size=c["hidden_size"],
        intermediate_size=c["intermediate_size"],
        num_layers=c["num_hidden_layers"],
        num_heads=c["num_attention_heads"],
        num_kv_heads=c.get("num_key_value_heads",
                           c["num_attention_heads"]),
        head_dim=c.get("head_dim"),
        rope_theta=c.get("rope_theta", 10000.0),
        rms_norm_eps=c.get("rms_norm_eps", 1e-5),
        max_position=c.get("max_position_embeddings", 8192),
        tie_word_embeddings=c.get("tie_word_embeddings", False),
        num_experts=c.get("num_local_experts", 0),
        num_experts_per_token=c.get("num_experts_per_tok", 0),
    )


# --------------------------- orbax checkpoints ----------------------------


def save_checkpoint(path: str, params: Params) -> None:
    """Write a native orbax checkpoint (sharded-restore capable)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()


def load_checkpoint(
    path: str,
    target: Optional[Params] = None,
    cfg: Optional[ModelConfig] = None,
    mesh=None,
) -> Params:
    """Restore an orbax checkpoint. With ``cfg`` (and optionally ``mesh``),
    the abstract restore target — shapes, dtypes, AND ``SpecLayout``
    shardings — is built via :func:`abstract_params`, so orbax writes each
    leaf straight onto its device shards with no host-replicated staging
    copy. An explicit ``target`` overrides the derived one."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if target is None and cfg is not None:
        target = abstract_params(cfg, mesh)
    if target is not None:
        return ckptr.restore(os.path.abspath(path), target)
    return ckptr.restore(os.path.abspath(path))
