"""Quantized serving: int8/fp8 weights + quantized paged KV cache.

Storage convention (one scheme for both weights and KV, so every consumer
— jitted matmuls, the Pallas kernel, kvbm offload, the disagg wire — can
dequantize with a single multiply):

* **Weights**: a quantized leaf is a dict ``{"q": <storage dtype>,
  "s": float32}`` replacing the plain array in the param pytree. Scales
  are per-output-channel: amax is taken over the *input* (contraction)
  axis — axis ``-2`` for every matmul weight in this model family
  (``[L, in, out]`` stacked dense, ``[L, E, in, out]`` stacked experts,
  ``[D, V]`` lm_head) — with ``keepdims=True`` so ``q * s`` broadcasts
  back to the full-precision shape without reshapes. Norm weights, the
  embedding table, and MoE router weights stay in the model dtype: they
  are tiny and sit on the accuracy-critical path.

* **KV cache**: K/V pages store ``kv_dtype`` elements; scales live in
  parallel per-layer caches ``"ks"``/``"vs"`` of shape
  ``[num_blocks, KV, block_size]`` float32 — one scale per (slot, head).
  Per-token scales (rather than shared per-block) keep every byte-parity
  invariant the engine already pins: a token's quantized bytes depend
  only on that token's K/V, never on which block neighbours it landed
  next to, so spec-decode and chunked-prefill replays stay bit-exact.

``"bf16"`` means *unquantized passthrough*: params and cache keep the
model dtype and every code path compiles the exact pre-quant jaxpr — the
default config pays zero numerics tax.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import ml_dtypes
import numpy as np

# dtypes accepted by EngineConfig.weight_dtype / kv_dtype
QUANT_DTYPES = ("int8", "fp8")

# largest representable magnitude per storage dtype; amax maps onto it
QMAX = {"int8": 127.0, "fp8": 448.0}  # fp8 = e4m3fn

_JNP_STORAGE = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}
_NP_STORAGE = {
    "int8": np.dtype(np.int8),
    "fp8": np.dtype(ml_dtypes.float8_e4m3fn),
}


def is_quantized(dtype: str) -> bool:
    """True for the 1-byte storage modes, False for "bf16" passthrough."""
    return dtype in QUANT_DTYPES


def storage_dtype(dtype: str):
    """jnp storage dtype for a quantized mode."""
    return _JNP_STORAGE[dtype]


def np_storage_dtype(dtype: str) -> np.dtype:
    """numpy storage dtype (host staging / wire / kvbm tiers)."""
    return _NP_STORAGE[dtype]


def kv_bytes_per_elem(dtype: str, model_dtype: str = "bfloat16") -> float:
    """KV-cache bytes per stored element, scale overhead included.

    Quantized pages cost 1 byte/elem plus one float32 scale per head_dim
    elements; callers pass head_dim via the capacity helpers below when
    the exact figure matters. Here we report the page byte only — the
    scale adds 4/head_dim bytes/elem (reported separately by bench).
    """
    if is_quantized(dtype):
        return 1.0
    return float(jnp.dtype(model_dtype).itemsize)


# --------------------------- weight quantization ---------------------------

# matmul weights quantized at load time; everything else (norms, embed,
# w_router) stays in the model dtype
QUANTIZED_LEAVES = frozenset(
    ["wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"]
)


def is_weight_leaf(name: str) -> bool:
    return name in QUANTIZED_LEAVES


def quantize_np(w: np.ndarray, dtype: str) -> Dict[str, np.ndarray]:
    """Quantize one host-staged tensor: per-output-channel scales over
    the contraction axis (-2), ``keepdims`` so dequant is one multiply."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    s = (amax / QMAX[dtype]).astype(np.float32)
    s[s == 0.0] = 1.0  # all-zero channels: keep q = 0 without 0/0
    q = wf / s
    if dtype == "int8":
        q = np.clip(np.rint(q), -127.0, 127.0)
    return {"q": q.astype(_NP_STORAGE[dtype]), "s": s}


def quantize_jnp(w: jnp.ndarray, dtype: str) -> Dict[str, jnp.ndarray]:
    """Device-side twin of :func:`quantize_np` (same rounding: rint)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    s = amax / QMAX[dtype]
    s = jnp.where(s == 0.0, 1.0, s).astype(jnp.float32)
    q = wf / s
    if dtype == "int8":
        q = jnp.clip(jnp.rint(q), -127.0, 127.0)
    return {"q": q.astype(_JNP_STORAGE[dtype]), "s": s}


def dequantize_np(leaf: Dict[str, np.ndarray],
                  dtype: str = "float32") -> np.ndarray:
    return (np.asarray(leaf["q"], np.float32) * leaf["s"]).astype(dtype)


def quantize_params(params: Dict[str, Any], weight_dtype: str
                    ) -> Dict[str, Any]:
    """Quantize a loaded (device or host) param tree in place-shape:
    matmul leaves become ``{"q", "s"}`` dicts; the rest pass through.
    Already-quantized trees (dict leaves) are returned unchanged so the
    engine can accept pre-quantized params from the streaming loader."""
    if not is_quantized(weight_dtype):
        return params
    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name == "layers":
            out[name] = {
                k: (quantize_jnp(v, weight_dtype)
                    if is_weight_leaf(k) and not isinstance(v, dict) else v)
                for k, v in leaf.items()
            }
        elif is_weight_leaf(name) and not isinstance(leaf, dict):
            out[name] = quantize_jnp(leaf, weight_dtype)
        else:
            out[name] = leaf
    return out


# ----------------------------- KV quantization -----------------------------


def kv_quantize(x: jnp.ndarray, kv_dtype: str
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize freshly-projected K or V rows ``[N, KV, hd]`` to the
    storage dtype with one float32 scale per (token, head): returns
    ``(q [N, KV, hd], s [N, KV])``. Deterministic per token — the bytes
    never depend on block placement."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = amax / QMAX[kv_dtype]
    s = jnp.where(s == 0.0, 1.0, s).astype(jnp.float32)
    q = xf / s[..., None]
    if kv_dtype == "int8":
        q = jnp.clip(jnp.rint(q), -127.0, 127.0)
    return q.astype(_JNP_STORAGE[kv_dtype]), s


def kv_dequantize(q: jnp.ndarray, s: jnp.ndarray,
                  dtype=jnp.float32) -> jnp.ndarray:
    """Invert :func:`kv_quantize`: ``q`` [..., hd] times ``s`` [...]."""
    return (q.astype(jnp.float32) * s[..., None].astype(jnp.float32)
            ).astype(dtype)


def kv_quantize_cache_np(cache: np.ndarray, kv_dtype: str
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side twin of :func:`kv_quantize` over a whole paged cache
    ``[NB, KV, bs, hd]``: returns ``(q same-shape storage, s [NB, KV, bs]
    f32)``.  Used by test harnesses to build quantized fixtures."""
    xf = np.asarray(cache, np.float32)
    amax = np.max(np.abs(xf), axis=-1)
    s = (amax / QMAX[kv_dtype]).astype(np.float32)
    s[s == 0.0] = 1.0
    q = xf / s[..., None]
    if kv_dtype == "int8":
        q = np.clip(np.rint(q), -127.0, 127.0)
    return q.astype(_NP_STORAGE[kv_dtype]), s


def kv_dequantize_cache_np(q: np.ndarray, s: np.ndarray,
                           dtype=np.float32) -> np.ndarray:
    """Invert :func:`kv_quantize_cache_np`."""
    return (np.asarray(q, np.float32) * s[..., None]).astype(dtype)
