"""Startup kernel auto-selection for ``attention_impl="auto"``.

BENCH_r05 measured the Pallas paged-attention decode kernel *losing* to the
XLA gathered-einsum path on real hardware (kernel_speedup 0.91) — which
path wins depends on generation/shape, so "auto" times both on the live
backend at engine startup and picks the winner.  The ragged kernel serves
three distinct shape classes (decode rows, spec ``[B, k+1]`` verify
windows, prefill chunks) whose arithmetic intensity differs wildly, so each
class is probed separately and gets its own ``attention_impl_{class}``
choice.  The probe is one small attention call per (impl, class) — tens of
ms total, not a model forward.

On non-TPU backends the choice is einsum without probing: Pallas only runs
in interpret mode there, which is orders of magnitude slower and would both
waste startup time and always lose anyway.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Tuple

import numpy as np

from ..utils.logging import get_logger
from .config import EngineConfig, ModelConfig

log = get_logger("autotune")


def _time_attention(fn, args, iters: int = 20) -> float:
    fn(*args).block_until_ready()  # warm (compile)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters * 1e3


def _probe_class(
    model_config: ModelConfig, engine_config: EngineConfig,
    B: int, T: int,
) -> dict:
    """Time ragged-Pallas vs gathered-einsum on a ``[B, T]`` chunk shape.

    Rows attend a full ``W * block_size`` context (the chunk is its last
    ``T`` tokens) — the worst case for the einsum path's gathered scores
    and the steady state for the kernel's block streaming.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.paged_attention import (
        paged_attention_decode, paged_attention_ragged,
    )
    from . import model as model_lib

    bs = engine_config.block_size
    W = max(2, min(8, engine_config.max_blocks_per_seq))
    KV = model_config.num_kv_heads
    H = model_config.num_heads
    hd = model_config.head_dim_
    NB = 1 + B * W
    rng = np.random.default_rng(0)
    dt = jnp.bfloat16 if model_config.dtype == "bfloat16" else jnp.float32
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), dt)
    k = jnp.asarray(rng.standard_normal((NB, KV, bs, hd)), dt)
    v = jnp.asarray(rng.standard_normal((NB, KV, bs, hd)), dt)
    tables = jnp.asarray(1 + np.arange(B * W).reshape(B, W), jnp.int32)
    lens = jnp.full((B,), W * bs, jnp.int32)

    if T == 1:
        kernel = jax.jit(functools.partial(
            paged_attention_decode, block_size=bs))

        def pallas_path(q, kc, vc, tables, lens):
            return kernel(q[:, 0], kc, vc, tables, lens)[:, None]
    else:
        q_start = jnp.arange(B + 1, dtype=jnp.int32) * T
        q_lens = jnp.full((B,), T, jnp.int32)
        kernel = jax.jit(functools.partial(
            paged_attention_ragged, block_size=bs, max_q_len=T))

        def pallas_path(q, kc, vc, tables, lens):
            out = kernel(q.reshape(B * T, H, hd), kc, vc, tables,
                         q_start, q_lens, lens)
            return out.reshape(B, T, H, hd)

    @jax.jit
    def einsum_path(q, kc, vc, tables, lens):
        k_all = jnp.take(kc, tables.reshape(-1), axis=0).reshape(
            B, W, KV, bs, hd
        ).transpose(0, 1, 3, 2, 4).reshape(B, W * bs, KV, hd)
        v_all = jnp.take(vc, tables.reshape(-1), axis=0).reshape(
            B, W, KV, bs, hd
        ).transpose(0, 1, 3, 2, 4).reshape(B, W * bs, KV, hd)
        pos = (lens[:, None] - T) + jnp.arange(T)[None, :]
        return model_lib._attention(q, k_all, v_all, pos)

    args = (q, k, v, tables, lens)
    pallas_ms = _time_attention(jax.jit(pallas_path), args)
    einsum_ms = _time_attention(einsum_path, args)
    return {
        "impl": "pallas" if pallas_ms < einsum_ms else "einsum",
        "B": B, "T": T,
        "pallas_ms": round(pallas_ms, 4),
        "einsum_ms": round(einsum_ms, 4),
        # >1 means the Pallas kernel is faster
        "ratio": round(einsum_ms / max(pallas_ms, 1e-9), 3),
    }


def probe_attention_impl(
    model_config: ModelConfig, engine_config: EngineConfig,
) -> Tuple[EngineConfig, dict]:
    """Resolve ``attention_impl="auto"`` → concrete per-class impls.

    Returns (engine_config with the winners substituted — ``attention_impl``
    carries the decode winner for back-compat and each
    ``attention_impl_{decode,spec,prefill}`` its class winner — plus a
    choice-info dict with the per-class times and ratios under "classes").
    Anything going wrong in a probe falls back to einsum — the
    always-correct reference path.
    """
    import jax

    if engine_config.attention_impl != "auto":
        return engine_config, {
            "impl": engine_config.attention_impl, "probed": False,
        }

    choice: dict = {"probed": False, "classes": {}}
    impls = {"decode": "einsum", "spec": "einsum", "prefill": "einsum"}
    if jax.default_backend() != "tpu":
        # interpret-mode Pallas is not a contender; don't burn startup time
        choice.update(impl="einsum", reason="non-tpu backend")
    else:
        B_dec = min(16, max(engine_config.decode_buckets))
        shapes = {"decode": (B_dec, 1)}
        if engine_config.spec_mode != "off":
            shapes["spec"] = (B_dec, engine_config.spec_k + 1)
        shapes["prefill"] = (4, min(256, max(engine_config.prefill_buckets)))
        for cls, (B, T) in shapes.items():
            try:
                info = _probe_class(model_config, engine_config, B, T)
                impls[cls] = info["impl"]
                choice["classes"][cls] = info
                choice["probed"] = True
            except Exception as e:
                choice["classes"][cls] = {
                    "impl": "einsum",
                    "reason": f"probe failed: {type(e).__name__}: {e}",
                }
        choice["impl"] = impls["decode"]
        # legacy top-level fields mirror the decode class (bench back-compat)
        dec = choice["classes"].get("decode", {})
        for key in ("pallas_ms", "einsum_ms", "ratio"):
            if key in dec:
                choice[key] = dec[key]
    log.info("attention_impl=auto resolved: %s", choice)
    resolved = dataclasses.replace(
        engine_config,
        attention_impl=choice.get("impl", "einsum"),
        attention_impl_decode=impls["decode"],
        attention_impl_spec=impls["spec"],
        attention_impl_prefill=impls["prefill"],
    )
    return resolved, choice
