"""Startup kernel auto-selection for ``attention_impl="auto"``.

BENCH_r05 measured the Pallas paged-attention decode kernel *losing* to the
XLA gathered-einsum path on real hardware (kernel_speedup 0.91) — which
path wins depends on generation/shape, so "auto" times both on the live
backend at engine startup and picks the winner. The probe is one small
decode-shaped attention call per impl (~tens of ms), not a model forward.

On non-TPU backends the choice is einsum without probing: Pallas only runs
in interpret mode there, which is orders of magnitude slower and would both
waste startup time and always lose anyway.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Tuple

import numpy as np

from ..utils.logging import get_logger
from .config import EngineConfig, ModelConfig

log = get_logger("autotune")


def _time_attention(fn, args, iters: int = 20) -> float:
    fn(*args).block_until_ready()  # warm (compile)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters * 1e3


def probe_attention_impl(
    model_config: ModelConfig, engine_config: EngineConfig,
) -> Tuple[EngineConfig, dict]:
    """Resolve ``attention_impl="auto"`` → a concrete impl.

    Returns (engine_config with the winner substituted, choice-info dict
    with the measured per-call times and ratio). Anything going wrong in
    the probe falls back to einsum — the always-correct reference path.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.paged_attention import paged_attention_decode
    from . import model as model_lib

    if engine_config.attention_impl != "auto":
        return engine_config, {
            "impl": engine_config.attention_impl, "probed": False,
        }

    choice: dict = {"probed": False}
    if jax.default_backend() != "tpu":
        # interpret-mode Pallas is not a contender; don't burn startup time
        choice.update(impl="einsum", reason="non-tpu backend")
    else:
        try:
            bs = engine_config.block_size
            B = min(16, max(engine_config.decode_buckets))
            W = max(2, min(8, engine_config.max_blocks_per_seq))
            KV = model_config.num_kv_heads
            H = model_config.num_heads
            hd = model_config.head_dim_
            NB = 1 + B * W
            rng = np.random.default_rng(0)
            dt = jnp.bfloat16 if model_config.dtype == "bfloat16" \
                else jnp.float32
            q = jnp.asarray(rng.standard_normal((B, H, hd)), dt)
            k = jnp.asarray(rng.standard_normal((NB, KV, bs, hd)), dt)
            v = jnp.asarray(rng.standard_normal((NB, KV, bs, hd)), dt)
            tables = jnp.asarray(
                1 + np.arange(B * W).reshape(B, W), jnp.int32)
            lens = jnp.full((B,), W * bs, jnp.int32)

            kernel = jax.jit(functools.partial(
                paged_attention_decode, block_size=bs))

            @jax.jit
            def einsum_path(q, kc, vc, tables, lens):
                k_all = jnp.take(kc, tables.reshape(-1), axis=0).reshape(
                    B, W, KV, bs, hd
                ).transpose(0, 1, 3, 2, 4).reshape(B, W * bs, KV, hd)
                v_all = jnp.take(vc, tables.reshape(-1), axis=0).reshape(
                    B, W, KV, bs, hd
                ).transpose(0, 1, 3, 2, 4).reshape(B, W * bs, KV, hd)
                pos = (lens - 1)[:, None]
                return model_lib._attention(q[:, None], k_all, v_all,
                                            pos)[:, 0]

            args = (q, k, v, tables, lens)
            pallas_ms = _time_attention(kernel, args)
            einsum_ms = _time_attention(einsum_path, args)
            impl = "pallas" if pallas_ms < einsum_ms else "einsum"
            choice.update(
                impl=impl, probed=True,
                pallas_ms=round(pallas_ms, 4),
                einsum_ms=round(einsum_ms, 4),
                # >1 means the Pallas kernel is faster
                ratio=round(einsum_ms / max(pallas_ms, 1e-9), 3),
            )
        except Exception as e:
            choice.update(impl="einsum",
                          reason=f"probe failed: {type(e).__name__}: {e}")
    log.info("attention_impl=auto resolved: %s", choice)
    resolved = dataclasses.replace(
        engine_config, attention_impl=choice["impl"])
    return resolved, choice
